"""HTTP/REST front-end (aiohttp) for the inference server core.

Implements the KServe-v2 REST surface incl. the binary tensor
extension and the shared-memory extension endpoints, mirroring the
URI scheme the reference client talks to (http_client.cc /v2/...).
Runs either on an existing asyncio loop or in a dedicated thread.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from aiohttp import web
from google.protobuf import json_format

from client_tpu import status_map
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.http_wire import (
    HEADER_LEN,
    compress_body,
    decode_infer_request,
    encode_infer_response,
)
from client_tpu.server import cancel as cancel_mod
from client_tpu.server.core import InferenceServerCore
from client_tpu.utils import InferenceServerException


def _error_response(error: InferenceServerException) -> web.Response:
    # Shed (503) and quota (429) responses carry Retry-After so
    # well-behaved clients (and LBs) back off instead of hammering a
    # saturated queue; value + rounding policy live in status_map.
    status = status_map.http_status(error.status())
    return web.json_response(
        {"error": error.message()}, status=status,
        headers=status_map.retry_after_headers(status, error),
    )


def _pb_json(message) -> web.Response:
    from client_tpu.server.http_embed import _int64_lists_to_ints

    return web.json_response(_int64_lists_to_ints(
        json_format.MessageToDict(message, preserving_proto_field_name=True)
    ))


# RFC 9110 Accept-Encoding negotiation shared with the native REST
# front-end's dispatcher.
from client_tpu.server.http_embed import _pick_encoding  # noqa: E402


def build_http_app(core: InferenceServerCore) -> web.Application:
    routes = web.RouteTableDef()

    def _run(fn, *args):
        """Execute a synchronous core call off the event loop."""
        return asyncio.get_running_loop().run_in_executor(None, fn, *args)

    @routes.get("/v2/health/live")
    async def health_live(request):
        return web.Response(status=200 if core.server_live() else 400)

    @routes.get("/v2/health/ready")
    async def health_ready(request):
        return web.Response(status=200 if core.server_ready() else 400)

    @routes.get("/v2/models/{model}/ready")
    @routes.get("/v2/models/{model}/versions/{version}/ready")
    async def model_ready(request):
        name = request.match_info["model"]
        ready = core.model_ready(
            name, request.match_info.get("version", "")
        )
        # Replica-serving models expose partial-degradation metadata:
        # the model stays ready while >=1 replica is healthy, and a
        # load balancer can weight by x-replica-healthy/-total without
        # a statistics round trip.
        headers = {}
        health = core.replica_health(name)
        if health is not None:
            headers["x-replica-healthy"] = str(health[0])
            headers["x-replica-total"] = str(health[1])
        return web.Response(status=200 if ready else 400, headers=headers)

    @routes.get("/metrics")
    async def metrics(request):
        # Content negotiation: exemplars (and the # EOF terminator)
        # are OpenMetrics syntax, served only to scrapers that ask for
        # that flavor — stock text-format parsers never see them.
        openmetrics = "application/openmetrics-text" in \
            request.headers.get("Accept", "")
        text = await _run(core.metrics_text, openmetrics)
        if openmetrics:
            return web.Response(
                body=text.encode("utf-8"),
                headers={"Content-Type": "application/openmetrics-text"
                                         "; version=1.0.0"
                                         "; charset=utf-8"})
        return web.Response(text=text,
                            content_type="text/plain", charset="utf-8")

    @routes.get("/v2/debug")
    async def debug_snapshot(request):
        # Live introspection (docs/flight_recorder.md): queue depth
        # per bucket/priority, in-flight requests with age + span
        # stage, replica health, KV/arena occupancy, SLO verdicts.
        doc = await _run(core.debug_snapshot,
                         request.query.get("model", ""))
        return web.json_response(doc)

    @routes.get("/v2/debug/flight")
    async def debug_flight(request):
        # Flight-ring dump: retroactively kept anomaly traces with
        # their full span trees (?model=M restricts to one model).
        doc = await _run(core.debug_flight,
                         request.query.get("model", ""))
        return web.json_response(doc)

    @routes.get("/v2/debug/profile")
    async def debug_profile(request):
        # On-demand bounded profiler capture (docs/
        # device_observability.md): blocks for the (clamped) window on
        # the executor, so the event loop keeps serving; concurrent
        # requests coalesce single-flight inside the core.
        try:
            duration_ms = int(request.query.get("duration_ms", "500"))
        except ValueError:
            duration_ms = 500
        doc = await _run(core.debug_profile, duration_ms,
                         request.query.get("model", ""))
        return web.json_response(doc)

    @routes.get("/v2")
    async def server_metadata(request):
        return _pb_json(core.server_metadata())

    @routes.get("/v2/models/{model}")
    @routes.get("/v2/models/{model}/versions/{version}")
    async def model_metadata(request):
        try:
            return _pb_json(
                core.model_metadata(
                    request.match_info["model"],
                    request.match_info.get("version", ""),
                )
            )
        except InferenceServerException as e:
            return _error_response(e)

    @routes.get("/v2/models/{model}/config")
    @routes.get("/v2/models/{model}/versions/{version}/config")
    async def model_config(request):
        try:
            response = core.model_config(
                request.match_info["model"],
                request.match_info.get("version", ""),
            )
            return _pb_json(response.config)
        except InferenceServerException as e:
            return _error_response(e)

    @routes.get("/v2/models/stats")
    @routes.get("/v2/models/{model}/stats")
    @routes.get("/v2/models/{model}/versions/{version}/stats")
    async def model_stats(request):
        try:
            return _pb_json(
                core.model_statistics(
                    request.match_info.get("model", ""),
                    request.match_info.get("version", ""),
                )
            )
        except InferenceServerException as e:
            return _error_response(e)

    @routes.post("/v2/repository/index")
    async def repository_index(request):
        body = await request.json() if request.can_read_body else {}
        index = core.repository_index(bool(body.get("ready", False)))
        return web.json_response(
            [
                {
                    "name": m.name,
                    "version": m.version,
                    "state": m.state,
                    "reason": m.reason,
                }
                for m in index.models
            ]
        )

    @routes.post("/v2/repository/models/{model}/load")
    async def repository_load(request):
        try:
            await _run(core.load_model, request.match_info["model"])
            return web.Response(status=200)
        except InferenceServerException as e:
            return _error_response(e)

    @routes.post("/v2/repository/models/{model}/unload")
    async def repository_unload(request):
        try:
            await _run(core.unload_model, request.match_info["model"])
            return web.Response(status=200)
        except InferenceServerException as e:
            return _error_response(e)

    # -- shared memory ---------------------------------------------------

    @routes.get("/v2/systemsharedmemory/status")
    @routes.get("/v2/systemsharedmemory/region/{name}/status")
    async def system_shm_status(request):
        status = core.system_shm_status(request.match_info.get("name", ""))
        return web.json_response(
            [
                {
                    "name": r.name,
                    "key": r.key,
                    "offset": r.offset,
                    "byte_size": r.byte_size,
                }
                for r in status.regions.values()
            ]
        )

    @routes.post("/v2/systemsharedmemory/region/{name}/register")
    async def system_shm_register(request):
        try:
            body = await request.json()
            core.register_system_shm(
                request.match_info["name"],
                body["key"],
                int(body.get("offset", 0)),
                int(body["byte_size"]),
            )
            return web.Response(status=200)
        except KeyError as e:
            return web.json_response(
                {"error": "missing field %s" % e},
                status=status_map.HTTP_BAD_REQUEST,
            )
        except InferenceServerException as e:
            return _error_response(e)

    @routes.post("/v2/systemsharedmemory/unregister")
    @routes.post("/v2/systemsharedmemory/region/{name}/unregister")
    async def system_shm_unregister(request):
        try:
            core.unregister_system_shm(request.match_info.get("name", ""))
            return web.Response(status=200)
        except InferenceServerException as e:
            return _error_response(e)

    @routes.get("/v2/tpusharedmemory/status")
    @routes.get("/v2/tpusharedmemory/region/{name}/status")
    async def tpu_shm_status(request):
        status = core.tpu_shm_status(request.match_info.get("name", ""))
        return web.json_response(
            [
                {
                    "name": r.name,
                    "device_id": r.device_id,
                    "byte_size": r.byte_size,
                }
                for r in status.regions.values()
            ]
        )

    @routes.post("/v2/tpusharedmemory/region/{name}/register")
    async def tpu_shm_register(request):
        import base64

        try:
            body = await request.json()
            raw_handle = base64.b64decode(body["raw_handle"]["b64"])
            core.register_tpu_shm(
                request.match_info["name"],
                raw_handle,
                int(body.get("device_id", 0)),
                int(body["byte_size"]),
            )
            return web.Response(status=200)
        except (KeyError, TypeError, ValueError) as e:
            return web.json_response(
                {"error": "malformed register request: %s" % e},
                status=status_map.HTTP_BAD_REQUEST,
            )
        except InferenceServerException as e:
            return _error_response(e)

    @routes.post("/v2/tpusharedmemory/unregister")
    @routes.post("/v2/tpusharedmemory/region/{name}/unregister")
    async def tpu_shm_unregister(request):
        try:
            core.unregister_tpu_shm(request.match_info.get("name", ""))
            return web.Response(status=200)
        except InferenceServerException as e:
            return _error_response(e)

    # -- trace / logging -------------------------------------------------

    @routes.get("/v2/trace/setting")
    @routes.get("/v2/models/{model}/trace/setting")
    async def get_trace(request):
        settings = core.trace_setting(request.match_info.get("model", ""), {})
        return web.json_response(
            {k: v if len(v) != 1 else v[0] for k, v in settings.items()}
        )

    @routes.post("/v2/trace/setting")
    @routes.post("/v2/models/{model}/trace/setting")
    async def post_trace(request):
        body = await request.json()
        updates = {
            k: (v if isinstance(v, list) else [v]) if v is not None else []
            for k, v in body.items()
        }
        settings = core.trace_setting(request.match_info.get("model", ""),
                                      updates)
        return web.json_response(
            {k: v if len(v) != 1 else v[0] for k, v in settings.items()}
        )

    @routes.get("/v2/logging")
    async def get_logging(request):
        return web.json_response(core.log_settings({}))

    @routes.post("/v2/logging")
    async def post_logging(request):
        body = await request.json()
        return web.json_response(core.log_settings(body))

    # -- generate (LLM extension) ---------------------------------------

    def _apply_tenant_header(request, infer_request) -> None:
        """x-tenant-id -> `tenant` parameter (an in-body parameter
        wins), so the generate/OpenAI routes carry quota identity like
        the /infer route."""
        tenant_header = request.headers.get("x-tenant-id")
        if tenant_header and "tenant" not in infer_request.parameters:
            infer_request.parameters["tenant"].string_param = tenant_header

    def _generate_request(request, body: bytes):
        """JSON body fields -> ModelInferRequest tensors by input name
        (shared codec: http_wire.build_generate_request)."""
        from client_tpu.protocol.http_wire import build_generate_request
        from client_tpu.server.core import mint_request_id

        model_name = request.match_info["model"]
        model = core.repository.get(model_name)
        infer_request = build_generate_request(
            model.inputs, model_name,
            request.match_info.get("version", ""), body)
        # Same correlation hygiene as the /infer route: an id for
        # trace/statistics joins, tenant identity for quotas.
        mint_request_id(infer_request)
        _apply_tenant_header(request, infer_request)
        return infer_request

    def _generate_json(response: pb.ModelInferResponse) -> dict:
        from client_tpu.protocol.http_wire import generate_response_json

        return generate_response_json(response)

    @routes.post("/v2/models/{model}/generate")
    @routes.post("/v2/models/{model}/versions/{version}/generate")
    async def generate(request):
        body = await request.read()
        try:
            infer_request = _generate_request(request, body)
            token = (core.cancel.mint(infer_request.id)
                     if core.cancel.enabled else None)
            try:
                response = await _run(core.infer, infer_request,
                                      request.headers.get("traceparent"),
                                      token)
            except asyncio.CancelledError:
                if token is not None:
                    token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
                raise
            return web.json_response(_generate_json(response))
        except InferenceServerException as e:
            return _error_response(e)

    @routes.post("/v2/models/{model}/generate_stream")
    @routes.post("/v2/models/{model}/versions/{version}/generate_stream")
    async def generate_stream(request):
        import json as _json

        body = await request.read()
        try:
            infer_request = _generate_request(request, body)
        except InferenceServerException as e:
            return _error_response(e)
        sse = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"}
        )
        await sse.prepare(request)
        loop = asyncio.get_running_loop()
        queue_: asyncio.Queue = asyncio.Queue()
        DONE = object()
        import threading

        cancelled = threading.Event()
        # W3C propagation parity with /infer: a caller-supplied
        # traceparent joins the stream's span tree (and thereby the
        # TTFT/ITL exemplars) to the client's trace.
        trace_context = request.headers.get("traceparent")
        token = (core.cancel.mint(infer_request.id)
                 if core.cancel.enabled else None)

        def _produce():
            generator = core.stream_infer(infer_request, trace_context,
                                          token)
            try:
                for stream_response in generator:
                    if cancelled.is_set():
                        break  # client gone: stop consuming the model
                    loop.call_soon_threadsafe(queue_.put_nowait,
                                              stream_response)
            except Exception as e:
                # errors raised before the generator's first yield must
                # still reach the client as an SSE error event
                error = pb.ModelStreamInferResponse(error_message=str(e))
                loop.call_soon_threadsafe(queue_.put_nowait, error)
            finally:
                generator.close()  # release the model promptly
                loop.call_soon_threadsafe(queue_.put_nowait, DONE)

        producer = loop.run_in_executor(None, _produce)
        try:
            while True:
                item = await queue_.get()
                if item is DONE:
                    break
                if item.error_message:
                    payload = {"error": item.error_message}
                else:
                    # suppress only the data-less final marker; data
                    # responses pass through whatever their outputs are
                    if not item.infer_response.outputs:
                        continue
                    payload = _generate_json(item.infer_response)
                await sse.write(
                    ("data: %s\n\n" % _json.dumps(payload)).encode()
                )
        except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
            # SSE transport gone mid-stream: the token reaps the LLM
            # lane at the next chunk boundary (pages + reservation
            # freed) instead of decoding the full budget into nowhere.
            if token is not None:
                token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
            cancelled.set()
            raise
        finally:
            cancelled.set()
            await producer
        await sse.write_eof()
        return sse

    # -- OpenAI-compatible endpoints (chat/completions over the LLM
    # models; the server-side counterpart of the reference perf
    # harness's openai client backend, client_backend/openai/) ----------

    def _openai_request(doc, prompt: str):
        model_name = doc.get("model") or ""
        if not model_name:
            raise InferenceServerException(
                "missing 'model'", status="INVALID_ARGUMENT")
        infer_request = pb.ModelInferRequest(model_name=model_name)
        from client_tpu.protocol.http_wire import _json_data_to_raw

        tensor = infer_request.inputs.add()
        tensor.name = "text_input"
        tensor.datatype = "BYTES"
        tensor.shape.extend([1])
        infer_request.raw_input_contents.append(
            _json_data_to_raw([prompt], "BYTES", "text_input"))
        max_tokens = doc.get("max_tokens") or doc.get(
            "max_completion_tokens")
        if max_tokens:
            tensor = infer_request.inputs.add()
            tensor.name = "max_tokens"
            tensor.datatype = "INT32"
            tensor.shape.extend([1])
            infer_request.raw_input_contents.append(
                _json_data_to_raw([int(max_tokens)], "INT32", "max_tokens"))
        from client_tpu.server.core import mint_request_id

        mint_request_id(infer_request)
        return infer_request

    def _openai_text(response: pb.ModelInferResponse) -> str:
        from client_tpu.protocol.http_wire import _raw_to_json_data

        for i, tensor in enumerate(response.outputs):
            if tensor.name == "text_output" and i < len(
                    response.raw_output_contents):
                data = _raw_to_json_data(
                    response.raw_output_contents[i], tensor.datatype)
                return "".join(str(d) for d in data)
        return ""

    async def _chat_completions(request):
        import json as _json

        try:
            doc = _json.loads(await request.read())
            messages = doc.get("messages") or []
            prompt = ""
            for message in messages:
                if message.get("role") == "user":
                    prompt = message.get("content") or ""
            infer_request = _openai_request(doc, prompt)
            _apply_tenant_header(request, infer_request)
        except InferenceServerException as e:
            return _error_response(e)
        except Exception as e:
            return web.json_response(
                {"error": {"message": str(e)}},
                status=status_map.HTTP_BAD_REQUEST)
        if doc.get("stream"):
            return await _openai_stream(
                request, infer_request, chat=True)
        try:
            response = await _run(core.infer, infer_request)
        except InferenceServerException as e:
            return _error_response(e)
        return web.json_response({
            "id": "chatcmpl-0",
            "object": "chat.completion",
            "model": infer_request.model_name,
            "choices": [{
                "index": 0,
                "message": {"role": "assistant",
                            "content": _openai_text(response)},
                "finish_reason": "stop",
            }],
        })

    async def _completions(request):
        import json as _json

        try:
            doc = _json.loads(await request.read())
            prompt = doc.get("prompt") or ""
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            infer_request = _openai_request(doc, prompt)
            _apply_tenant_header(request, infer_request)
        except InferenceServerException as e:
            return _error_response(e)
        except Exception as e:
            return web.json_response(
                {"error": {"message": str(e)}},
                status=status_map.HTTP_BAD_REQUEST)
        if doc.get("stream"):
            return await _openai_stream(
                request, infer_request, chat=False)
        try:
            response = await _run(core.infer, infer_request)
        except InferenceServerException as e:
            return _error_response(e)
        return web.json_response({
            "id": "cmpl-0",
            "object": "text_completion",
            "model": infer_request.model_name,
            "choices": [{
                "index": 0,
                "text": _openai_text(response),
                "finish_reason": "stop",
            }],
        })

    async def _openai_stream(request, infer_request, chat: bool):
        """SSE chunks in the OpenAI streaming shape, fed by the
        decoupled model stream (same producer pattern as
        generate_stream)."""
        import json as _json
        import threading

        sse = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"}
        )
        await sse.prepare(request)
        loop = asyncio.get_running_loop()
        queue_: asyncio.Queue = asyncio.Queue()
        DONE = object()
        cancelled = threading.Event()
        trace_context = request.headers.get("traceparent")

        def _produce():
            generator = core.stream_infer(infer_request, trace_context)
            try:
                for stream_response in generator:
                    if cancelled.is_set():
                        break
                    loop.call_soon_threadsafe(
                        queue_.put_nowait, stream_response)
            except Exception as e:
                error = pb.ModelStreamInferResponse(error_message=str(e))
                loop.call_soon_threadsafe(queue_.put_nowait, error)
            finally:
                generator.close()
                loop.call_soon_threadsafe(queue_.put_nowait, DONE)

        producer = loop.run_in_executor(None, _produce)
        obj = "chat.completion.chunk" if chat else "text_completion"
        try:
            while True:
                item = await queue_.get()
                if item is DONE:
                    break
                if item.error_message:
                    payload = {"error": {"message": item.error_message}}
                else:
                    if not item.infer_response.outputs:
                        continue
                    token = _openai_text(item.infer_response)
                    final = item.infer_response.parameters[
                        "triton_final_response"].bool_param
                    choice = {"index": 0,
                              "finish_reason": "stop" if final else None}
                    if chat:
                        choice["delta"] = {"content": token}
                    else:
                        choice["text"] = token
                    payload = {"id": "chatcmpl-0", "object": obj,
                               "model": infer_request.model_name,
                               "choices": [choice]}
                await sse.write(
                    ("data: %s\n\n" % _json.dumps(payload)).encode())
        except (ConnectionResetError, ConnectionError,
                asyncio.CancelledError):
            cancelled.set()
            raise
        finally:
            cancelled.set()
            await producer
        await sse.write(b"data: [DONE]\n\n")
        await sse.write_eof()
        return sse

    routes.post("/v1/chat/completions")(_chat_completions)
    routes.post("/v1/completions")(_completions)

    # -- inference -------------------------------------------------------

    @routes.post("/v2/cancel/{id}")
    async def cancel_by_id(request):
        """Explicit wire cancellation: flips the CancelToken of the
        in-flight request with this id (the HTTP twin of a gRPC RPC
        cancel). 404 for unknown/already-finished ids — cancellation
        of completed work is not an error a client can act on, but the
        distinction is observable."""
        found = await _run(core.cancel_request, request.match_info["id"])
        return web.json_response({"cancelled": bool(found)},
                                 status=200 if found else 404)

    @routes.post("/v2/models/{model}/infer")
    @routes.post("/v2/models/{model}/versions/{version}/infer")
    async def infer(request):
        body = await request.read()
        header_length = request.headers.get(HEADER_LEN)
        # Compressed request bodies (Content-Encoding gzip/deflate)
        # are already decompressed by aiohttp's request parser.
        try:
            infer_request = decode_infer_request(
                body,
                request.match_info["model"],
                request.match_info.get("version", ""),
                int(header_length) if header_length else None,
            )
            from client_tpu.server.core import mint_request_id

            mint_request_id(infer_request)
            _apply_tenant_header(request, infer_request)
            token = (core.cancel.mint(infer_request.id)
                     if core.cancel.enabled else None)
            try:
                # W3C trace-context propagation: a caller-supplied
                # traceparent joins the server span tree to the
                # client's.
                response = await _run(core.infer, infer_request,
                                      request.headers.get("traceparent"),
                                      token)
            except asyncio.CancelledError:
                # aiohttp cancels the handler task when the client's
                # transport closes mid-request: flip the token so the
                # worker thread's in-flight core call unwinds at its
                # next stage boundary and frees everything it holds.
                if token is not None:
                    token.cancel(cancel_mod.REASON_CLIENT_DISCONNECT)
                raise
            binary_prefs = {}
            default_binary = False  # pure-JSON clients get JSON back
            for tensor in infer_request.outputs:
                if "binary_data" in tensor.parameters:
                    binary_prefs[tensor.name] = tensor.parameters[
                        "binary_data"
                    ].bool_param
            if "binary_data_output" in infer_request.parameters:
                default_binary = infer_request.parameters[
                    "binary_data_output"
                ].bool_param
            payload, json_len = encode_infer_response(
                response, binary_prefs, default_binary
            )
            headers = {}
            if json_len is not None:
                headers[HEADER_LEN] = str(json_len)
            # Per-call response compression: honor the client's
            # explicit Accept-Encoding preference (reference allows
            # gzip/deflate per request).
            algorithm = _pick_encoding(
                request.headers.get("Accept-Encoding", ""))
            if algorithm:
                payload = compress_body(payload, algorithm)
                headers["Content-Encoding"] = algorithm
            return web.Response(
                body=payload,
                headers=headers,
                content_type=(
                    "application/octet-stream" if json_len is not None
                    else "application/json"
                ),
            )
        except InferenceServerException as e:
            from client_tpu.server.chaos import ChaosDropError

            if isinstance(e, ChaosDropError):
                # Injected connection drop: sever the TCP transport so
                # the client sees a reset mid-request, not an error
                # body — the failure mode a crashed pod produces.
                if request.transport is not None:
                    request.transport.close()
                raise ConnectionResetError("chaos drop") from e
            return _error_response(e)

    app = web.Application(client_max_size=1024**3)
    app.add_routes(routes)
    return app


class HttpServerThread:
    """Runs the aiohttp app on a dedicated thread + event loop."""

    def __init__(self, core: InferenceServerCore, host: str, port: int):
        self._core = core
        self._host = host
        self._port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._runner: Optional[web.AppRunner] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self.port: Optional[int] = None

    def start(self):
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("HTTP server failed to start (timeout)")
        if self._startup_error is not None:
            raise RuntimeError(
                "HTTP server failed to start"
            ) from self._startup_error
        return self

    def _serve(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _up():
            app = build_http_app(self._core)
            # handler_cancellation: aiohttp >= 3.9 no longer cancels
            # handler tasks on client disconnect by default — without
            # it the client-disconnect cancellation source (the
            # CancelledError handlers in build_http_app) never fires
            # and an abandoned request computes to completion.
            self._runner = web.AppRunner(app, handler_cancellation=True)
            await self._runner.setup()
            # shutdown_timeout mirrors the gRPC server's stop grace:
            # aiohttp's 60s default would park stop() on every live
            # keep-alive connection — a "killed" replica must actually
            # go away promptly.
            site = web.TCPSite(self._runner, self._host, self._port,
                               shutdown_timeout=1.0)
            await site.start()
            server = site._server
            self.port = server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(_up())
        except BaseException as e:
            self._startup_error = e
            self._started.set()
            return
        self._started.set()
        self._loop.run_forever()

    def stop(self):
        if self._loop is None:
            return

        async def _down():
            if self._runner is not None:
                await self._runner.cleanup()

        import concurrent.futures

        try:
            asyncio.run_coroutine_threadsafe(
                _down(), self._loop).result(timeout=10)
        except (TimeoutError, concurrent.futures.TimeoutError):
            # Cleanup wedged on a stubborn connection: stop the loop
            # anyway — the listener sockets are already closed and a
            # dead thread is better than a hung caller.
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)


def start_http_server_thread(
    core: InferenceServerCore, host: str = "0.0.0.0", port: int = 8000
) -> HttpServerThread:
    return HttpServerThread(core, host, port).start()
