"""Device-axis observability: per-model HBM ledger, busy-time/duty-
cycle counters, XLA compile telemetry, and on-demand profiler capture.

The request axis is covered end to end (spans, histograms, the flight
recorder); the *device* axis used to stop at three whole-chip
``tpu_hbm_*`` gauges rendered inline by ``core.metrics_text`` with a
bare ``except: pass``. This module owns that axis:

* :class:`DeviceLedger` — every HBM allocation site registers a
  ``(model, component)`` row (model weights at load, KV page pools,
  TPU arena regions, per-replica instances) and releases it on
  teardown, so ``tpu_hbm_model_bytes{model,component}`` attributes
  device memory to its owner. A residual ``unattributed`` row closes
  the gap to ``tpu_hbm_used_bytes`` whenever the runtime reports it,
  so the rows always sum to the whole-chip gauge within tolerance.
  ``register``/``release`` is a paired protocol the tpulint
  resource-pairing checker enforces (the PR-7 tenant-admission
  guarantee class) — a new allocation site cannot silently leak rows.
* **Busy time** — ``tpu_device_busy_us_total{device}`` accumulates the
  device-side durations the execution layers already measure (fused
  ``batch_execute`` compute, direct ``device_execute``, per-replica
  executions routed to their device), so Prometheus ``rate()`` yields
  duty cycle; ``tpu_device_duty_cycle{device}`` derives the same over
  a sliding window for scrape-free consumers (the ROADMAP-4
  autoscaler's scale-up signal).
* **Compile telemetry** — a ``jax.monitoring`` listener attributes
  every XLA backend compile to the model whose execution (or load
  warmup, or background prefill compile) triggered it, via a
  thread-local scope the execution layers push. Families:
  ``tpu_compile_total{model,shape}`` (shape-bucket fingerprint,
  cardinality-bounded) and the ``tpu_compile_duration_us{model}``
  histogram — the batcher's pow2-padding policy's compile cost,
  finally measurable. A recompile storm (N compiles for one model
  inside a short window) stamps the model's flight ring
  (``mark_incident``) and logs.
* :class:`ProfilerCapture` — ``GET /v2/debug/profile?duration_ms=``:
  a bounded ``jax.profiler`` trace written under a server-owned
  directory, plus a span-derived chrome trace of the same window
  (always produced; the graceful arm when the platform profiler is
  unsupported). Concurrent captures coalesce single-flight.

One :class:`DeviceStats` instance per process (``devstats.get()``):
the device axis is process-global — several in-process cores share
the same chips, so they share the same ledger and counters.
``enabled=False`` turns every hot-path recording into a cheap early
return (the paired-A/B overhead arm, gated <2% like telemetry and
flight capture).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

_LOG = logging.getLogger("client_tpu.server.devstats")

# Ledger cardinality bounds: models are operator-configured (bounded),
# but a hostile/looping caller must not mint rows without bound —
# past the caps new names fold into one overflow row (the qos.py
# tenant pattern).
MAX_LEDGER_MODELS = 256
MAX_LEDGER_COMPONENTS = 64
OVERFLOW_ROW = "overflow"

# Compile-telemetry bounds: shape-bucket fingerprints are derived from
# execution shapes (pow2-padded, so naturally few), but unbounded
# dynamic shapes must not grow /metrics — past the cap new
# fingerprints fold into "other".
MAX_COMPILE_SHAPES = 32
OVERFLOW_SHAPE = "other"

# Recompile-storm detector: >= STORM_COMPILES compiles for ONE model
# inside STORM_WINDOW_S stamps the model's flight ring and logs; the
# detector re-arms after the window so a sustained storm stamps once
# per window, not once per compile.
STORM_COMPILES = 5
STORM_WINDOW_S = 30.0

# Duty-cycle derivation window (seconds) and its bucket resolution.
DUTY_WINDOW_S = 10.0
_DUTY_SLOT_S = 0.1

# Profiler capture bounds: the duration is clamped so a typo'd
# duration_ms cannot hold the single-flight slot (and a jax trace
# buffer) for minutes.
PROFILE_MIN_MS = 10
PROFILE_MAX_MS = 10_000
PROFILE_DEFAULT_MS = 500
# Span-tap bound: requests captured into the fallback chrome trace.
PROFILE_MAX_TAPPED = 512

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_UNATTRIBUTED = "unattributed"


def _array_leaf_bytes(value) -> int:
    """Sum of ``jax.Array`` leaf nbytes in an arbitrary pytree-ish
    value (0 when jax is unavailable or the value holds none)."""
    try:
        import jax

        total = 0
        for leaf in jax.tree_util.tree_leaves(value):
            if isinstance(leaf, jax.Array):
                total += int(leaf.nbytes)
        return total
    except Exception:  # noqa: BLE001 — measurement is best-effort
        return 0


def model_array_bytes(model) -> int:
    """Exact ``jax.Array`` nbytes resident in a model instance (the
    cross-check against the memory_stats() delta at load): walks the
    instance's attribute values and sums device-array leaves."""
    attrs = getattr(model, "__dict__", None)
    if not attrs:
        return 0
    total = 0
    for value in attrs.values():
        total += _array_leaf_bytes(value)
    return total


def shape_fingerprint(inputs) -> str:
    """Bounded shape-bucket fingerprint of an execution's input dict:
    the compile-relevant signature (sorted names are dropped — shapes
    alone identify the XLA specialization for a fixed model)."""
    try:
        parts = []
        for name in sorted(inputs):
            value = inputs[name]
            shape = getattr(value, "shape", None)
            if shape is None:
                continue
            parts.append("x".join(str(int(d)) for d in shape))
        return "b" + "_".join(parts)[:64] if parts else "b?"
    except Exception:  # noqa: BLE001 — a label, never a failure
        return "b?"


class LedgerRow:
    """Handle for one registered allocation: releasing it subtracts
    exactly what the register added (idempotent — a double release is
    a no-op, never negative accounting)."""

    __slots__ = ("model", "component", "nbytes", "_released")

    def __init__(self, model: str, component: str, nbytes: int):
        self.model = model
        self.component = component
        self.nbytes = int(nbytes)
        self._released = False


class DeviceLedger:
    """Per-model HBM attribution: (model, component) -> bytes.

    Rows aggregate — registering the same (model, component) twice
    holds the sum, and each :class:`LedgerRow` handle releases its own
    contribution, so many arena regions (say) share one bounded row.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # model -> component -> [bytes, exact_bytes]
        self._rows: Dict[str, Dict[str, List[int]]] = {}
        # model -> component -> bytes currently paged out to host: the
        # component still *exists* (its row did not vanish at
        # page-out), it just occupies zero device bytes until restore.
        self._paged: Dict[str, Dict[str, int]] = {}
        # High-water mark of the attributed total, advanced at every
        # register — so a pool allocated and freed between two
        # observations still shows in take_peak().
        self._peak = 0

    def _total_locked(self) -> int:
        return sum(entry[0]
                   for components in self._rows.values()
                   for entry in components.values())

    def _fold(self, model: str, component: str):
        """Cardinality bounds (caller holds the lock)."""
        if model not in self._rows and len(self._rows) >= MAX_LEDGER_MODELS:
            model = OVERFLOW_ROW
        components = self._rows.setdefault(model, {})
        if component not in components and \
                len(components) >= MAX_LEDGER_COMPONENTS:
            component = OVERFLOW_ROW
        return model, component, components

    def register(self, model: str, component: str, nbytes: int,
                 exact_nbytes: Optional[int] = None
                 ) -> Optional[LedgerRow]:
        """Adds ``nbytes`` to the (model, component) row; returns the
        handle ``release`` takes (None for empty allocations — nothing
        to account, nothing to leak)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return None
        model = str(model)
        component = str(component)
        with self._lock:
            model, component, components = self._fold(model, component)
            entry = components.setdefault(component, [0, 0])
            entry[0] += nbytes
            entry[1] += int(exact_nbytes if exact_nbytes is not None
                            else nbytes)
            current = self._total_locked()
            if current > self._peak:
                self._peak = current
        return LedgerRow(model, component, nbytes)

    def release(self, row: Optional[LedgerRow]) -> None:
        if row is None or row._released:
            return
        row._released = True
        with self._lock:
            components = self._rows.get(row.model)
            if components is None:
                return
            entry = components.get(row.component)
            if entry is None:
                return
            entry[0] = max(entry[0] - row.nbytes, 0)
            if entry[0] <= 0:
                components.pop(row.component, None)
                if not components:
                    self._rows.pop(row.model, None)

    def release_component(self, model: str, component: str) -> int:
        """Drops one whole (model, component) row (weights replacement
        at re-load); returns the bytes dropped."""
        with self._lock:
            components = self._rows.get(model)
            if components is None:
                return 0
            entry = components.pop(component, None)
            if not components:
                self._rows.pop(model, None)
            if entry is None:
                return 0
            return entry[0]

    def release_model(self, model: str) -> int:
        """Drops every row of ``model`` (unload teardown); returns the
        bytes dropped."""
        with self._lock:
            self._paged.pop(str(model), None)
            components = self._rows.pop(str(model), None)
            if not components:
                return 0
            return sum(entry[0] for entry in components.values())

    def mark_paged(self, row: Optional[LedgerRow]) -> int:
        """Moves a row's bytes to the paged-out side table: the device
        total drops (the bytes now live in host memory) but the
        (model, component) pair stays visible — ``/v2/debug`` and the
        hbm allocator keep naming it until restore or release. Returns
        the bytes moved (0 for an empty or already-released row)."""
        if row is None or row._released:
            return 0
        self.release(row)
        with self._lock:
            components = self._paged.setdefault(row.model, {})
            components[row.component] = \
                components.get(row.component, 0) + row.nbytes
        return row.nbytes

    def mark_paged_bytes(self, model: str, component: str,
                         nbytes: int) -> int:
        """Parks ``nbytes`` straight into the paged-out side table —
        the row-less variant of :meth:`mark_paged`, for a component
        whose register was never observed (load-measure failure) but
        whose bytes did move to host: the paged set still names it.
        Returns the bytes parked (0 for empty sizes)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return 0
        with self._lock:
            components = self._paged.setdefault(str(model), {})
            components[str(component)] = \
                components.get(str(component), 0) + nbytes
        return nbytes

    def unmark_paged(self, model: str, component: str,
                     nbytes: Optional[int] = None) -> int:
        """Removes up to ``nbytes`` (all when None) from the paged-out
        side table — restore re-registers a live row, release drops
        the bytes entirely. Returns the bytes removed."""
        with self._lock:
            components = self._paged.get(str(model))
            if not components:
                return 0
            held = components.get(str(component), 0)
            taken = held if nbytes is None else min(held, int(nbytes))
            remaining = held - taken
            if remaining > 0:
                components[str(component)] = remaining
            else:
                components.pop(str(component), None)
                if not components:
                    self._paged.pop(str(model), None)
            return taken

    def paged_snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {model: dict(components)
                    for model, components in self._paged.items()}

    def take_peak(self) -> int:
        """High-water mark of the attributed total since the last
        call (re-armed at the current total) — the per-bench-stage
        `hbm_peak_bytes` sample, catching pools that alloc and free
        entirely inside one stage."""
        with self._lock:
            current = self._total_locked()
            peak = max(self._peak, current)
            self._peak = current
            return peak

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                model: {component: entry[0]
                        for component, entry in components.items()}
                for model, components in self._rows.items()
            }

    def model_bytes(self, model: str) -> Dict[str, int]:
        with self._lock:
            components = self._rows.get(str(model))
            if not components:
                return {}
            return {component: entry[0]
                    for component, entry in components.items()}

    def total(self) -> int:
        with self._lock:
            return self._total_locked()


class _LoadMeasure:
    """Context manager around one model load: measures the per-device
    ``memory_stats()`` delta (exact on accelerators), cross-checked
    against the instance's summed ``jax.Array`` nbytes (the only
    signal on backends whose ``memory_stats()`` is None — the CPU
    sim), and registers the ``weights`` ledger row on success. Also
    pushes the compile-attribution scope so load-time warmup compiles
    land on the model, not on ``unattributed``."""

    def __init__(self, stats: "DeviceStats", name: str):
        self._stats = stats
        self._name = name
        self.model = None  # caller sets once the instance exists
        self._before = 0
        self._scope = None
        self.row: Optional[LedgerRow] = None

    def __enter__(self) -> "_LoadMeasure":
        # Loads serialize on the measurement lock: two concurrent
        # loads would each see the other's allocations inside their
        # memory_stats() delta and both weights rows would over-count
        # (reentrant: an ensemble load may load composing models).
        self._stats._load_lock.acquire()
        self._before = self._stats.hbm_used_total()
        self._scope = self._stats.compile_scope(self._name, "load")
        self._scope.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            if self._scope is not None:
                self._scope.__exit__(exc_type, exc, tb)
            if exc_type is not None:
                return False
            return self._register()
        finally:
            self._stats._load_lock.release()

    def _register(self) -> bool:
        exact = model_array_bytes(self.model) if self.model is not None \
            else 0
        after = self._stats.hbm_used_total()
        delta = max(after - self._before, 0) if after else 0
        nbytes = delta or exact
        ledger = self._stats.ledger
        # A re-load replaces the previous instance's weights row
        # instead of stacking on top of it.
        ledger.release_component(self._name, "weights")
        self.row = ledger.register(self._name, "weights", nbytes,
                                   exact_nbytes=exact)
        return False


class ProfilerCapture:
    """Bounded on-demand capture with single-flight coalescing.

    Always produces a span-derived chrome trace of the window (every
    request completing while armed is tapped, bounded); additionally
    runs ``jax.profiler`` when the platform supports it and reports
    its output directory. Writes under a server-owned directory."""

    def __init__(self, stats: "DeviceStats",
                 directory: Optional[str] = None):
        self._stats = stats
        self._dir = directory
        self._dir_lock = threading.Lock()
        self._lock = threading.Lock()
        self._inflight: Optional[tuple] = None
        self._seq = 0
        # Span tap: armed during a capture window; the core forwards
        # every finished request trace here (cheap flag check when
        # disarmed).
        self.armed = False
        self._tap_lock = threading.Lock()
        self._tapped: List[dict] = []
        self._tap_dropped = 0
        self._tap_model = ""
        self.capture_count = 0
        self.coalesced_count = 0
        # Bound on arming the jax profiler: the FIRST start in a
        # process imports heavy profiler deps (tensorflow, ~10s cold
        # and far worse under GIL-saturating load) — a capture must
        # not block on it. Past the bound the capture proceeds with
        # the span arm; the import keeps warming in the background, so
        # a later capture gets the jax arm cheaply.
        self.jax_start_timeout_s = 5.0

    def directory(self) -> str:
        with self._dir_lock:
            if self._dir is None:
                import tempfile

                self._dir = tempfile.mkdtemp(prefix="client_tpu_profile_")
            return self._dir

    # -- span tap ---------------------------------------------------------

    def tap(self, model_name: str, request_id: str, trace) -> None:
        """Called by the core for every request finishing while a
        capture is armed (bounded; serialization happens here, off
        the capture thread but only during the window)."""
        if not self.armed:
            return
        if self._tap_model and model_name != self._tap_model:
            return
        try:
            record = {
                "model": str(model_name),
                "request_id": str(request_id),
                "spans": [span.as_dict() for span in trace.snapshot()],
            }
        except Exception:  # noqa: BLE001 — profiling never fails serving
            return
        with self._tap_lock:
            if not self.armed:
                return
            if len(self._tapped) >= PROFILE_MAX_TAPPED:
                self._tap_dropped += 1
                return
            self._tapped.append(record)

    # -- capture ----------------------------------------------------------

    def capture(self, duration_ms: int = PROFILE_DEFAULT_MS,
                model_name: str = "") -> dict:
        """One bounded capture; concurrent calls coalesce onto the
        in-flight window and share its result."""
        try:
            duration_ms = int(duration_ms)
        except (TypeError, ValueError):
            duration_ms = PROFILE_DEFAULT_MS
        duration_ms = max(PROFILE_MIN_MS, min(duration_ms,
                                              PROFILE_MAX_MS))
        with self._lock:
            inflight = self._inflight
            if inflight is not None:
                event, box, leader_ms = inflight
            else:
                event, box = threading.Event(), {}
                self._inflight = (event, box, duration_ms)
        if inflight is not None:
            # Follower: wait the leader out (bounded by its window
            # plus profiler teardown slack), then share its result.
            event.wait(leader_ms / 1000.0 + 30.0)
            with self._lock:
                self.coalesced_count += 1
            result = dict(box) if box else {"error": "capture failed"}
            result["coalesced"] = True
            return result
        try:
            box.update(self._capture(duration_ms, model_name))
        except Exception as e:  # noqa: BLE001 — the endpoint reports,
            box["error"] = str(e)  # never raises a 500 for a trace
        finally:
            with self._lock:
                self._inflight = None
                self.capture_count += 1
            event.set()
        return dict(box, coalesced=False)

    def _start_jax_trace(self, jax_dir: str) -> tuple:
        """Starts ``jax.profiler.start_trace`` on a worker thread,
        bounded by ``jax_start_timeout_s``. Returns ``(started,
        error)``; a start that completes only after the bound stops
        itself immediately (profile sessions are exclusive — an
        abandoned open session would fail every later capture)."""
        box: dict = {}
        done = threading.Event()
        lock = threading.Lock()

        def run():
            ok = False
            try:
                import jax

                jax.profiler.start_trace(jax_dir)
                ok = True
            except Exception as e:  # noqa: BLE001 — the graceful
                box["error"] = "unsupported on this platform: %s" % e
            with lock:
                box["ok"] = ok
                done.set()
                abandoned = box.get("abandoned", False)
            if ok and abandoned:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001
                    pass

        threading.Thread(target=run, daemon=True,
                         name="devstats-profile-start").start()
        if done.wait(self.jax_start_timeout_s):
            if box.get("ok"):
                return True, None
            return False, box.get("error", "start failed")
        with lock:
            if done.is_set():  # landed while we were timing out
                if box.get("ok"):
                    return True, None
                return False, box.get("error", "start failed")
            box["abandoned"] = True
        return False, ("profiler start exceeded %.0fs (deps still "
                       "importing) — span-derived trace only; retry "
                       "for the jax arm" % self.jax_start_timeout_s)

    def _capture(self, duration_ms: int, model_name: str) -> dict:
        out_dir = self.directory()
        with self._lock:
            self._seq += 1
            seq = self._seq
        with self._tap_lock:
            self._tapped = []
            self._tap_dropped = 0
            self._tap_model = str(model_name or "")
        jax_dir = os.path.join(out_dir, "jax_%d" % seq)
        started, jax_error = self._start_jax_trace(jax_dir)
        if not started:
            jax_dir = None
        self.armed = True
        try:
            time.sleep(duration_ms / 1000.0)
        finally:
            self.armed = False
            if started:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception as e:  # noqa: BLE001
                    jax_error = str(e)
                    jax_dir = None
        with self._tap_lock:
            tapped, self._tapped = self._tapped, []
            dropped = self._tap_dropped
        chrome_path = os.path.join(out_dir,
                                   "profile_%d.trace.json" % seq)
        models: Dict[str, int] = {}
        events: List[dict] = []
        from client_tpu.server.tracing import chrome_span_events

        for index, record in enumerate(tapped):
            models[record["model"]] = models.get(record["model"], 0) + 1
            events.extend(chrome_span_events(
                record["spans"], record["model"], index,
                "req %s" % record["request_id"],
                {"request_id": record["request_id"]}))
        try:
            with open(chrome_path, "w") as f:
                json.dump(events, f)
        except OSError as e:
            chrome_path = None
            jax_error = jax_error or str(e)
        return {
            "duration_ms": duration_ms,
            "model": str(model_name or ""),
            "chrome_trace": chrome_path,
            "jax_trace_dir": jax_dir,
            "jax_supported": started and jax_dir is not None,
            "jax_error": jax_error,
            "mode": "jax+spans" if jax_dir else "spans",
            "requests_captured": len(tapped),
            "requests_dropped": dropped,
            "models": models,
        }


class DeviceStats:
    """The process-wide device-observability registry (see module
    docstring). Prefer :func:`get` over constructing one — the device
    axis is shared by every core in the process; tests build private
    instances."""

    def __init__(self, enabled: Optional[bool] = None):
        if enabled is None:
            enabled = os.environ.get(
                "CLIENT_TPU_DEVSTATS", "").strip().lower() not in (
                    "off", "0", "false", "disabled")
        self.enabled = bool(enabled)
        self.ledger = DeviceLedger()
        self.profiler = ProfilerCapture(self)
        self._lock = threading.Lock()
        # Serializes load measurements (see _LoadMeasure.__enter__);
        # reentrant because an ensemble load loads composing models.
        self._load_lock = threading.RLock()
        # device key -> cumulative busy ns.
        self._busy_ns: Dict[str, int] = {}
        # device key -> deque of [slot, ns] duty-window buckets.
        self._busy_window: Dict[str, deque] = {}
        # model -> {"count", "ns", "shapes": {fp: count},
        #           "hist": LatencyHistogram, "storm": deque,
        #           "storm_fired": mono}
        self._compiles: Dict[str, dict] = {}
        self._incident_hooks: List[Callable[[str, str], None]] = []
        self._tls = threading.local()
        self._device_keys: Optional[List[str]] = None
        # Scrape-error accounting: a broken memory_stats() backend is
        # a counter + one warning log, never an invisible empty family.
        self.scrape_errors = 0
        self._scrape_warned = False
        # Bench stage sampling (hbm peak + compile delta per stage).
        self._stage_peak = 0
        self._stage_compiles_base = 0
        register_compile_listener()

    # -- devices ----------------------------------------------------------

    def device_keys(self) -> List[str]:
        """Stable per-device labels (``CPU-0`` / ``TPU-3`` — the same
        uuid scheme the tpu_hbm_* families have always used)."""
        keys = self._device_keys
        if keys is None:
            try:
                import jax

                keys = ["%s-%d" % (d.platform.upper(), d.id)
                        for d in jax.local_devices()]
            except Exception:  # noqa: BLE001 — no runtime: one slot
                keys = ["DEVICE-0"]
            if not keys:
                keys = ["DEVICE-0"]
            self._device_keys = keys
        return keys

    def device_key_for_index(self, index: int) -> str:
        """Replica index -> device label (replicas map onto local
        devices round-robin — on a one-device host every replica's
        busy time lands on that device, which is the truth)."""
        keys = self.device_keys()
        return keys[int(index) % len(keys)]

    def hbm_used_total(self) -> int:
        """Sum of ``bytes_in_use`` over local devices (0 when the
        backend reports none — the CPU sim)."""
        total = 0
        try:
            import jax

            for device in jax.local_devices():
                stats = device.memory_stats() or {}
                total += int(stats.get("bytes_in_use") or 0)
        except Exception:  # noqa: BLE001
            self._note_scrape_error()
            return 0
        return total

    def _note_scrape_error(self) -> None:
        with self._lock:
            self.scrape_errors += 1
            warned, self._scrape_warned = self._scrape_warned, True
        if not warned:
            _LOG.warning(
                "device memory_stats() scrape failed — tpu_hbm_* "
                "families will be empty; tpu_device_stats_errors_total "
                "counts further failures (logged once per process)")

    # -- model load measurement ------------------------------------------

    def measure_model_load(self, name: str) -> _LoadMeasure:
        return _LoadMeasure(self, str(name))

    # -- busy time / duty cycle ------------------------------------------

    def record_busy(self, device_key: Optional[str], ns: int) -> None:
        """Accumulates one execution's device-side duration.
        ``device_key=None`` lands on the first local device (the
        non-replicated single-device arm)."""
        if not self.enabled or ns <= 0:
            return
        if device_key is None:
            device_key = self.device_keys()[0]
        now = time.monotonic()
        slot = int(now / _DUTY_SLOT_S)
        horizon = slot - int(DUTY_WINDOW_S / _DUTY_SLOT_S)
        with self._lock:
            self._busy_ns[device_key] = \
                self._busy_ns.get(device_key, 0) + int(ns)
            window = self._busy_window.get(device_key)
            if window is None:
                window = deque()
                self._busy_window[device_key] = window
            if window and window[-1][0] == slot:
                window[-1][1] += int(ns)
            else:
                window.append([slot, int(ns)])
            while window and window[0][0] < horizon:
                window.popleft()

    def replica_busy(self, index: int, ns: int) -> None:
        """ReplicaSet busy hook: one successful execution on replica
        ``index``, routed to its device."""
        if not self.enabled:
            return
        self.record_busy(self.device_key_for_index(index), ns)

    def busy_snapshot(self) -> Dict[str, int]:
        """device key -> cumulative busy microseconds (monotonic)."""
        with self._lock:
            return {key: ns // 1000 for key, ns in self._busy_ns.items()}

    def duty_cycle(self) -> Dict[str, float]:
        """device key -> busy fraction over the sliding window. On the
        CPU sim several 'device' executions can overlap in wall time,
        so the value may exceed 1.0 — that reads as oversubscription,
        not an error."""
        now = time.monotonic()
        slot = int(now / _DUTY_SLOT_S)
        horizon = slot - int(DUTY_WINDOW_S / _DUTY_SLOT_S)
        out: Dict[str, float] = {}
        with self._lock:
            for key, window in self._busy_window.items():
                while window and window[0][0] < horizon:
                    window.popleft()
                busy_ns = sum(entry[1] for entry in window)
                out[key] = busy_ns / (DUTY_WINDOW_S * 1e9)
        return out

    # -- compile telemetry ------------------------------------------------

    def _compile_entry(self, model: str) -> dict:
        entry = self._compiles.get(model)
        if entry is None:
            from client_tpu.server.telemetry import LatencyHistogram

            entry = self._compiles.setdefault(model, {
                "count": 0, "ns": 0, "shapes": {},
                "hist": LatencyHistogram(),
                "storm": deque(maxlen=64), "storm_fired": 0.0,
            })
        return entry

    def add_incident_hook(self, hook: Callable[[str, str], None]) -> None:
        """Registers a recompile-storm sink (the core wires the flight
        recorder's ``mark_incident`` here)."""
        with self._lock:
            if hook not in self._incident_hooks:
                self._incident_hooks.append(hook)

    def set_thread_model(self, model: str) -> None:
        """Sticky attribution for a model-owned worker thread (LLM
        decode scheduler, background prefill compiles): XLA compiles
        on this thread attribute to ``model`` unless a narrower scope
        is active."""
        self._tls.default = (str(model), "worker")

    @contextlib.contextmanager
    def _scope_cm(self, model: str, fingerprint: Optional[str]):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        entry = (str(model), str(fingerprint) if fingerprint else "b?")
        stack.append(entry)
        wall0 = time.monotonic_ns()
        try:
            yield
        finally:
            stack.pop()
            if _LISTENER_MODE != "monitoring":
                # First-call fallback when jax.monitoring is absent:
                # the first execution of a new shape bucket carries
                # the compile, so its wall time is the honest upper
                # bound.
                self._record_first_call(entry,
                                        time.monotonic_ns() - wall0)

    def compile_scope(self, model: str, fingerprint: Optional[str] = None):
        """Context manager the execution layers wrap device dispatch
        in; compiles observed inside attribute to (model,
        fingerprint)."""
        if not self.enabled:
            return contextlib.nullcontext()
        return self._scope_cm(model, fingerprint)

    def _record_first_call(self, entry, wall_ns: int) -> None:
        model, fingerprint = entry
        with self._lock:
            compile_entry = self._compile_entry(model)
            if fingerprint in compile_entry["shapes"]:
                return
        self.record_compile(model, fingerprint, wall_ns,
                            source="first_call")

    def current_scope(self):
        """(model, fingerprint) for the calling thread: innermost
        explicit scope, else the thread's sticky model, else
        unattributed."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1]
        default = getattr(self._tls, "default", None)
        if default is not None:
            return default
        return (_UNATTRIBUTED, "b?")

    def record_compile(self, model: str, fingerprint: str, ns: int,
                       source: str = "monitoring") -> None:
        """One XLA backend compile attributed to ``model``/shape."""
        if not self.enabled:
            return
        ns = max(int(ns), 0)
        fire_storm = False
        with self._lock:
            entry = self._compile_entry(str(model))
            entry["count"] += 1
            entry["ns"] += ns
            shapes = entry["shapes"]
            fingerprint = str(fingerprint or "b?")
            if fingerprint not in shapes and \
                    len(shapes) >= MAX_COMPILE_SHAPES:
                fingerprint = OVERFLOW_SHAPE
            shapes[fingerprint] = shapes.get(fingerprint, 0) + 1
            now = time.monotonic()
            storm = entry["storm"]
            storm.append(now)
            while storm and now - storm[0] > STORM_WINDOW_S:
                storm.popleft()
            # The unattributed pseudo-model aggregates compiles from
            # unscoped threads across ALL models — a storm there names
            # no culprit and stamps no ring, so it never fires.
            if model != _UNATTRIBUTED \
                    and len(storm) >= STORM_COMPILES and \
                    now - entry["storm_fired"] > STORM_WINDOW_S:
                entry["storm_fired"] = now
                fire_storm = True
                storm_count = len(storm)
            hooks = list(self._incident_hooks)
        entry["hist"].observe(ns / 1000.0)
        if fire_storm:
            label = ("recompile_storm compiles=%d window_s=%d"
                     % (storm_count, int(STORM_WINDOW_S)))
            _LOG.warning(
                "model '%s': %d XLA compiles inside %ds — recompile "
                "storm (shape-bucket churn? check the batcher's "
                "padding policy and the model's dynamic shapes)",
                model, storm_count, int(STORM_WINDOW_S))
            for hook in hooks:
                try:
                    hook(str(model), label)
                except Exception:  # noqa: BLE001 — stamping is
                    pass  # advisory

    def compile_snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                model: {
                    "count": entry["count"],
                    "ns": entry["ns"],
                    "shapes": dict(entry["shapes"]),
                }
                for model, entry in self._compiles.items()
            }

    def compile_total(self) -> int:
        with self._lock:
            return sum(entry["count"]
                       for entry in self._compiles.values())

    # -- statistics-proto / debug views -----------------------------------

    def model_device_snapshot(self, model: str) -> Optional[dict]:
        """The DeviceStatistics block for one model (None when the
        ledger and compile tracker both know nothing about it)."""
        components = self.ledger.model_bytes(model)
        with self._lock:
            entry = self._compiles.get(str(model))
            compile_count = entry["count"] if entry else 0
            compile_ns = entry["ns"] if entry else 0
        if not components and not compile_count:
            return None
        return {
            "hbm_bytes": sum(components.values()),
            "components": sorted(components.items()),
            "compile_count": compile_count,
            "compile_ns": compile_ns,
        }

    def debug_snapshot(self) -> dict:
        """The ``devices`` section of GET /v2/debug (cardinality-
        bounded: devices, ledger rows, per-model compile counts)."""
        used_rows = {}
        limit_rows = {}
        try:
            import jax

            for device in jax.local_devices():
                key = "%s-%d" % (device.platform.upper(), device.id)
                stats = device.memory_stats() or {}
                used = stats.get("bytes_in_use")
                limit = stats.get("bytes_limit")
                if used is not None:
                    used_rows[key] = int(used)
                if limit:
                    limit_rows[key] = int(limit)
        except Exception:  # noqa: BLE001
            self._note_scrape_error()
        ledger = self.ledger.snapshot()
        ledger_total = sum(sum(components.values())
                           for components in ledger.values())
        compiles = self.compile_snapshot()
        return {
            "hbm_used_bytes": used_rows,
            "hbm_total_bytes": limit_rows,
            "ledger": ledger,
            "ledger_paged_out": self.ledger.paged_snapshot(),
            "ledger_total_bytes": ledger_total,
            "unattributed_bytes": max(
                sum(used_rows.values()) - ledger_total, 0)
            if used_rows else None,
            "busy_us": self.busy_snapshot(),
            "duty_cycle": {key: round(value, 6)
                           for key, value in self.duty_cycle().items()},
            "compiles": {
                model: {"count": entry["count"],
                        "shapes": entry["shapes"]}
                for model, entry in sorted(compiles.items())
            },
            "scrape_errors": self.scrape_errors,
            "profiler": {
                "armed": bool(self.profiler.armed),
                "captures": self.profiler.capture_count,
                "coalesced": self.profiler.coalesced_count,
            },
        }

    # -- bench stage sampling ---------------------------------------------

    def stage_sample(self) -> dict:
        """Per-bench-stage device sample: the HBM high-water mark
        since the last call — the ledger's register-time peak (catches
        a pool allocated AND freed inside the stage) combined with the
        runtime used-bytes endpoint samples — plus the compile-count
        delta."""
        used = self.hbm_used_total()
        ledger_peak = self.ledger.take_peak()
        current = max(used, self.ledger.total())
        compiles = self.compile_total()
        with self._lock:
            peak = max(self._stage_peak, used, ledger_peak)
            delta = compiles - self._stage_compiles_base
            self._stage_peak = current
            self._stage_compiles_base = compiles
        return {"hbm_peak_bytes": int(peak),
                "compile_count": max(int(delta), 0)}

    # -- exposition --------------------------------------------------------

    def render_metrics(self) -> List[str]:
        """Prometheus exposition lines for every device family (the
        block that used to live inline in ``core.metrics_text`` behind
        a bare ``except: pass`` — failures now count and log)."""
        lines: List[str] = []

        def family(name, kind, help_text, rows):
            if not rows:
                return
            lines.append("# HELP %s %s" % (name, help_text))
            lines.append("# TYPE %s %s" % (name, kind))
            lines.extend(rows)

        used_rows, total_rows, util_rows = [], [], []
        used_total = 0
        used_seen = False
        try:
            import jax

            for device in jax.local_devices():
                uuid = "%s-%d" % (device.platform.upper(), device.id)
                label = '{tpu_uuid="%s"}' % uuid
                mem = device.memory_stats() or {}
                used = mem.get("bytes_in_use")
                limit = mem.get("bytes_limit")
                if used is not None:
                    used_seen = True
                    used_total += int(used)
                    used_rows.append("tpu_hbm_used_bytes%s %d"
                                     % (label, used))
                if limit:
                    total_rows.append("tpu_hbm_total_bytes%s %d"
                                      % (label, limit))
                    if used is not None:
                        util_rows.append("tpu_hbm_utilization%s %.6f"
                                         % (label, used / limit))
        except Exception:  # noqa: BLE001 — metrics never take the
            self._note_scrape_error()  # server down — but they COUNT
        family("tpu_hbm_used_bytes", "gauge",
               "Accelerator HBM bytes in use", used_rows)
        family("tpu_hbm_total_bytes", "gauge",
               "Accelerator HBM capacity in bytes", total_rows)
        family("tpu_hbm_utilization", "gauge",
               "Fraction of accelerator HBM in use", util_rows)

        model_rows = []
        ledger_total = 0
        ledger_rows = self.ledger.snapshot()  # ONE consistent view
        for model in sorted(ledger_rows):
            components = ledger_rows[model]
            for component in sorted(components):
                nbytes = components[component]
                ledger_total += nbytes
                model_rows.append(
                    'tpu_hbm_model_bytes{model="%s",component="%s"} %d'
                    % (model, component, nbytes))
        if used_seen:
            residual = max(used_total - ledger_total, 0)
            model_rows.append(
                'tpu_hbm_model_bytes{model="%s",component="residual"} '
                '%d' % (_UNATTRIBUTED, residual))
        family("tpu_hbm_model_bytes", "gauge",
               "HBM bytes attributed per model and component by the "
               "device ledger (weights, kv_pages, arena, replicas); "
               "the unattributed/residual row closes the gap to "
               "tpu_hbm_used_bytes", model_rows)

        busy_rows = [
            'tpu_device_busy_us_total{device="%s"} %d' % (key, us)
            for key, us in sorted(self.busy_snapshot().items())
        ]
        family("tpu_device_busy_us_total", "counter",
               "Cumulative device-side execution time (fused batch "
               "compute + direct executes + per-replica executions); "
               "rate() yields duty cycle", busy_rows)
        duty_rows = [
            'tpu_device_duty_cycle{device="%s"} %.6f' % (key, value)
            for key, value in sorted(self.duty_cycle().items())
        ]
        family("tpu_device_duty_cycle", "gauge",
               "Busy fraction over a %ds sliding window (may exceed 1 "
               "when simulated devices overlap executions)"
               % int(DUTY_WINDOW_S), duty_rows)

        compiles = self.compile_snapshot()
        compile_rows = []
        for model in sorted(compiles):
            for shape in sorted(compiles[model]["shapes"]):
                compile_rows.append(
                    'tpu_compile_total{model="%s",shape="%s"} %d'
                    % (model, shape, compiles[model]["shapes"][shape]))
        family("tpu_compile_total", "counter",
               "XLA compiles attributed per model and shape-bucket "
               "fingerprint (bounded cardinality; recompile storms "
               "stamp the flight ring)", compile_rows)
        hist_rows = []
        with self._lock:
            entries = [(model, entry["hist"])
                       for model, entry in sorted(self._compiles.items())]
        from client_tpu.server.telemetry import ServerTelemetry

        for model, hist in entries:
            snap = hist.snapshot()
            if snap["count"]:
                hist_rows.extend(ServerTelemetry._histogram_rows(
                    "tpu_compile_duration_us", 'model="%s"' % model,
                    snap, with_exemplars=False))
        family("tpu_compile_duration_us", "histogram",
               "XLA compile wall time per model (histogram)",
               hist_rows)

        family("tpu_device_stats_errors_total", "counter",
               "Device-stats scrape failures (memory_stats() backend "
               "errors; logged once per process)",
               ["tpu_device_stats_errors_total %d" % self.scrape_errors])
        return lines


# -- process-wide singleton + jax.monitoring listener ----------------------

_SINGLETON: Optional[DeviceStats] = None
_SINGLETON_LOCK = threading.Lock()
_LISTENER_LOCK = threading.Lock()
_LISTENER_MODE = "unregistered"


def get() -> DeviceStats:
    """The process-wide DeviceStats (devices are process-global; all
    in-process cores share one ledger and one set of counters)."""
    global _SINGLETON
    if _SINGLETON is None:
        with _SINGLETON_LOCK:
            if _SINGLETON is None:
                _SINGLETON = DeviceStats()
    return _SINGLETON


def _on_jax_event(event: str, duration_secs: float, **_kwargs) -> None:
    if event != _COMPILE_EVENT:
        return
    stats = _SINGLETON
    if stats is None or not stats.enabled:
        return
    model, fingerprint = stats.current_scope()
    stats.record_compile(model, fingerprint,
                         int(duration_secs * 1e9))


def register_compile_listener() -> str:
    """Registers the process-wide jax.monitoring compile listener once
    (idempotent); returns the resulting mode ("monitoring" or
    "first_call" when jax.monitoring is unavailable)."""
    global _LISTENER_MODE
    with _LISTENER_LOCK:
        if _LISTENER_MODE != "unregistered":
            return _LISTENER_MODE
        try:
            import jax.monitoring

            jax.monitoring.register_event_duration_secs_listener(
                _on_jax_event)
            _LISTENER_MODE = "monitoring"
        except Exception:  # noqa: BLE001 — fall back to first-call
            _LISTENER_MODE = "first_call"  # timing inside the scopes
        return _LISTENER_MODE


def listener_mode() -> str:
    return _LISTENER_MODE
