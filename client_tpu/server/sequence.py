"""Sequence-batching scheduler: stateful sequence serving with
device-resident implicit state.

The TPU-first counterpart of Triton's sequence batcher (the scheduler
behind `simple_sequence` / `dyna_sequence` and perf_analyzer's
sequence load modes). It sits between the front-ends and the PR-1
pipelined dynamic batcher and owns everything a correlated stream of
requests needs that a stateless scheduler cannot provide:

* **Slot assignment.** Each live sequence holds one of
  ``max_candidate_sequences`` slots from its first step
  (``sequence_start``) to its last (``sequence_end``). Two strategies,
  parsed from the model's ``sequence_batching`` config:

  - **Direct** — the slot is pinned for the sequence lifetime and
    every step executes as its own model call (the contract for models
    that manage their own per-correlation-id state, like
    `simple_sequence`).
  - **Oldest** — each step dispatches into the model's dynamic
    batcher, oldest sequence first, so concurrent steps from DISTINCT
    sequences fuse into one device execution instead of N singles
    (the Orca-style cross-sequence step fusion that dominates
    stateful-serving throughput). ``preferred_batch_size`` and
    ``max_candidate_sequences`` bound the fused step batch.

* **Per-sequence ordering.** Steps of one sequence execute in arrival
  order (a ticket turnstile per slot); steps of distinct sequences run
  concurrently. This replaces transport-level chaining as the ordering
  authority — the gRPC stream path still submits in arrival order, but
  correctness no longer depends on it.

* **Control-input injection.** Models that declare ``control_input``
  get CORRID / START / END / READY tensors injected into every step
  (shaped ``[batch, 1]`` for batching models), matching the reference
  `dyna_sequence` contract; the client never sends them.

* **Implicit state** (``sequence_batching.state``). Per-slot state
  tensors live in HBM as ``jax.Array``s between steps: step N's state
  output is handed to step N+1's execution as a device array — state
  never round-trips through the ~65 ms relay fetch path (the
  TPU-native analogue of the reference's CUDA-shm state story), and
  models can donate the buffer into the next XLA call.

* **Backlog admission.** When every slot is busy a new sequence start
  waits in the backlog, governed by the model's PR-2 queue policy:
  ``max_queue_size`` bounds the backlog (overflow rejected
  UNAVAILABLE) and ``default_queue_policy_timeout_us`` (or the
  per-request ``timeout`` parameter) expires waiting starts
  DEADLINE_EXCEEDED.

* **Idle reclamation.** A sequence idle longer than
  ``max_sequence_idle_microseconds`` loses its slot (freeing it for
  the backlog); subsequent steps fail "sequence ... not started".
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from client_tpu.server import tracing as spantrace
from client_tpu import status_map
from client_tpu.utils import InferenceServerException, triton_to_np_dtype

NANOS_PER_US = 1_000

CONTROL_START = "CONTROL_SEQUENCE_START"
CONTROL_END = "CONTROL_SEQUENCE_END"
CONTROL_READY = "CONTROL_SEQUENCE_READY"
CONTROL_CORRID = "CONTROL_SEQUENCE_CORRID"

# Slots when the model declares sequence_batching without sizing it.
DEFAULT_CANDIDATE_SEQUENCES = 32


class ControlSpec:
    """One injected control tensor (name + kind + dtype)."""

    __slots__ = ("name", "kind", "datatype")

    def __init__(self, name: str, kind: str, datatype: str = "INT32"):
        self.name = name
        self.kind = kind
        self.datatype = datatype


class StateSpec:
    """One implicit-state tensor pair (model reads input_name, writes
    output_name; the scheduler carries the value between steps)."""

    __slots__ = ("input_name", "output_name", "datatype", "dims")

    def __init__(self, input_name: str, output_name: str,
                 datatype: str = "FP32", dims=(1,)):
        self.input_name = input_name
        self.output_name = output_name
        self.datatype = datatype
        self.dims = tuple(int(d) for d in dims)


class _Slot:
    """One live sequence: its slot id, device-resident state, and the
    ticket turnstile that serializes its steps."""

    __slots__ = ("index", "corrid", "state", "last_step_ns", "next_ticket",
                 "serving", "ended", "reclaimed", "abandoned")

    def __init__(self, index: int, corrid):
        self.index = index
        self.corrid = corrid
        self.state: Dict[str, object] = {}
        self.last_step_ns = time.monotonic_ns()
        self.next_ticket = 0   # next ticket to hand out
        self.serving = 0       # ticket currently allowed to execute
        self.ended = False     # sequence_end step has been admitted
        self.reclaimed = False
        # Tickets whose waiter was cancelled mid-wait: the turnstile
        # auto-advances past them in _release_turn. A cancelled step
        # must NOT bump `serving` itself — mid-wait its ticket is not
        # the one being served, and stealing the increment would strand
        # the live waiter behind it.
        self.abandoned: set = set()


def _not_started(model_name: str, corrid) -> InferenceServerException:
    return InferenceServerException(
        "sequence %s not started for model '%s' (no sequence_start, or "
        "the slot was reclaimed after max_sequence_idle_microseconds)"
        % (corrid, model_name),
        status="INVALID_ARGUMENT",
    )


class SequenceScheduler:
    """One scheduler per sequence-batched model.

    ``batcher`` is the model's DynamicBatcher (or None); the oldest
    strategy dispatches steps through it so concurrent sequences fuse.
    ``reject_hook`` / ``timeout_hook`` feed the PR-2 queue-policy drop
    counters.
    """

    def __init__(self, model, batcher=None,
                 reject_hook: Optional[Callable[[], None]] = None,
                 timeout_hook: Optional[Callable[[], None]] = None,
                 execution_target=None):
        self._model = model
        # Direct-strategy steps execute here. An instance-group model
        # passes its ReplicaSet proxy. Sticky routing engages only for
        # _pass_params models (no declared controls/state): their
        # steps carry sequence_id through to the proxy, which pins the
        # sequence to one replica — the model keeps per-corrid state
        # INSIDE the executable, so hopping fault domains would lose
        # it. Models with declared controls/state strip sequence_*
        # before execution and route freely: their state lives in the
        # scheduler's slot and travels with the inputs, so any replica
        # can execute any step.
        self._target = execution_target if execution_target is not None \
            else model
        self._batcher = batcher
        self._reject_hook = reject_hook
        self._timeout_hook = timeout_hook
        self._strategy = str(
            getattr(model, "sequence_strategy", "direct") or "direct"
        ).lower()
        candidates = int(getattr(model, "max_candidate_sequences", 0) or 0)
        self._slot_total = candidates if candidates > 0 \
            else DEFAULT_CANDIDATE_SEQUENCES
        self._idle_ns = max(
            int(getattr(model, "max_sequence_idle_us", 0) or 0), 0
        ) * NANOS_PER_US
        self._controls = _control_specs(model)
        self._states = _state_specs(model)
        # Backlog admission reuses the model's queue policy: bound +
        # wait deadline (0 = unbounded / wait forever).
        self._backlog_max = max(int(getattr(model, "max_queue_size", 0)), 0)
        self._default_timeout_ns = max(
            int(getattr(model, "default_queue_policy_timeout_us", 0)), 0
        ) * NANOS_PER_US
        self._allow_timeout_override = bool(
            getattr(model, "allow_timeout_override", True))
        # Models without declared controls/state manage their own state
        # keyed by the sequence_* request parameters — those must reach
        # model.infer, and fusing such steps would execute the bucket
        # with the leader's params, corrupting every other sequence.
        self._pass_params = not (self._controls or self._states)
        self._fuse = (self._strategy == "oldest" and batcher is not None
                      and not self._pass_params)
        self._cv = threading.Condition()
        self._sequences: "OrderedDict[object, _Slot]" = OrderedDict()
        self._free_slots: List[int] = list(range(self._slot_total))
        self._backlog = 0
        self._stopping = False
        # lifetime counters (ModelStatistics.sequence_stats)
        self._started_total = 0
        self._completed_total = 0
        self._reclaimed_total = 0
        self._step_total = 0
        self._fused_step_total = 0
        self._reaper: Optional[threading.Thread] = None
        if self._idle_ns > 0:
            self._reaper = threading.Thread(
                target=self._reap_loop, daemon=True,
                name="sequence-reaper-%s" % getattr(model, "name", "?"))
            self._reaper.start()

    # -- lifecycle --------------------------------------------------------

    def stop(self) -> None:
        """Rejects new work and wakes every backlogged start (they fail
        UNAVAILABLE); in-flight steps finish through the batcher/model
        they were already dispatched to."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._reaper is not None:
            self._reaper.join(timeout=5)

    # -- request path -----------------------------------------------------

    def infer(self, inputs: Dict[str, np.ndarray], params: dict,
              batch: int, trace=None, cancel=None):
        """Executes one sequence step; returns
        ``(outputs, queue_ns, executions)`` where executions follows
        the dynamic batcher's leader accounting (0 for fused riders).
        ``trace`` is the request's RequestTrace when sampled: the slot
        wait and (direct-strategy) device execution record spans, and
        fused steps carry the trace into the dynamic batcher.
        ``cancel`` is the request's CancelToken (or None): a cancelled
        step abandons its backlog wait or turnstile ticket without
        wedging the sequence's later steps.
        """
        corrid = params.get("sequence_id")
        start = bool(params.get("sequence_start"))
        end = bool(params.get("sequence_end"))
        entry_ns = time.monotonic_ns()
        handle = (cancel.on_cancel(self._wake_waiters)
                  if cancel is not None else None)
        try:
            slot, ticket = self._admit(corrid, start, entry_ns, params,
                                       cancel=cancel)
            try:
                self._await_turn(slot, ticket, start, cancel=cancel)
            except Exception as e:
                # A cancelled mid-wait step already abandoned its
                # ticket in _await_turn; bumping `serving` here would
                # steal the live turn owner's increment.
                if getattr(e, "cancel_stage", None) is None:
                    self._release_turn(slot, end=False)
                raise
        finally:
            if handle is not None:
                cancel.remove_callback(handle)
        turn_ns = time.monotonic_ns()
        queue_ns = turn_ns - entry_ns
        if trace is not None:
            trace.add_timed(
                spantrace.SPAN_SEQUENCE_WAIT, entry_ns, turn_ns,
                {"slot": slot.index, "corrid": str(corrid),
                 "start": start, "end": end})
        try:
            exec_inputs = dict(inputs)
            if self._controls:
                self._inject_controls(exec_inputs, batch, corrid, start, end)
            if self._states:
                self._attach_state(exec_inputs, slot, batch, start)
            if self._fuse:
                exec_params = {
                    k: v for k, v in params.items()
                    if not k.startswith("sequence_")
                }
                outputs, fuse_queue_ns, leader = self._batcher.infer(
                    exec_inputs, exec_params, batch, trace=trace,
                    queue_from_ns=turn_ns if trace is not None else 0,
                    cancel=cancel)
                queue_ns += fuse_queue_ns
                executions = 1 if leader else 0
                with self._cv:
                    self._fused_step_total += 1
            else:
                exec_span = (trace.begin(
                    spantrace.SPAN_DEVICE_EXECUTE,
                    attrs={"sequence_step": True})
                    if trace is not None else None)
                exec_params = params if self._pass_params else {
                    k: v for k, v in params.items()
                    if not k.startswith("sequence_")
                }
                if cancel is not None and cancel.cancelled():
                    cancel.raise_if_cancelled("queue")
                outputs = self._target.infer(exec_inputs, exec_params)
                if exec_span is not None:
                    trace.end(exec_span)
                executions = 1
            if self._states:
                outputs = self._extract_state(outputs, slot)
            with self._cv:
                self._step_total += 1
            return outputs, queue_ns, executions
        finally:
            self._release_turn(slot, end)

    # -- admission --------------------------------------------------------

    def _timeout_ns_for(self, params: dict) -> int:
        timeout_ns = self._default_timeout_ns
        if self._allow_timeout_override:
            override = params.get("timeout")
            if override is not None:
                try:
                    timeout_ns = max(int(override), 0) * NANOS_PER_US
                except (TypeError, ValueError):
                    pass
        return timeout_ns

    def _wake_waiters(self) -> None:
        """CancelToken wakeup: backlog and turnstile waits sleep on the
        scheduler CV, so a cancel must poke it to be seen promptly."""
        with self._cv:
            self._cv.notify_all()

    def _admit(self, corrid, start: bool, entry_ns: int, params: dict,
               cancel=None):
        """Returns (slot, ticket) for this step, allocating a slot on
        sequence_start (waiting in the backlog when none is free)."""
        model_name = getattr(self._model, "name", "?")
        with self._cv:
            while True:
                if self._stopping:
                    raise status_map.retryable_error(
                        "server is shutting down", retry_after_s=1.0)
                self._reclaim_locked(time.monotonic_ns())
                slot = self._sequences.get(corrid)
                if slot is not None:
                    if not start and slot.ended:
                        raise _not_started(model_name, corrid)
                    # live corrid: non-start steps join it; a start
                    # restarts in place (Triton semantics —
                    # _attach_state ignores stale state on start).
                    # Duplicate concurrent starts for one corrid land
                    # here too: the loser of the allocation race joins
                    # the winner's slot instead of minting a second.
                    ticket = slot.next_ticket
                    slot.next_ticket += 1
                    return slot, ticket
                if not start:
                    raise _not_started(model_name, corrid)
                if self._free_slots:
                    index = self._free_slots.pop(0)
                    slot = _Slot(index, corrid)
                    self._sequences[corrid] = slot
                    self._started_total += 1
                    ticket = slot.next_ticket
                    slot.next_ticket += 1
                    return slot, ticket
                # Backlog wait releases the lock; loop to re-check the
                # world (slot freed, duplicate start won, stopping).
                self._wait_for_slot_locked(model_name, entry_ns, params,
                                           cancel=cancel)

    def _wait_for_slot_locked(self, model_name: str, entry_ns: int,
                              params: dict, cancel=None) -> None:
        """Backlog admission under the PR-2 queue policy (caller holds
        the lock; returns with a slot free or raises)."""
        if self._backlog_max > 0 and self._backlog >= self._backlog_max:
            if self._reject_hook is not None:
                try:
                    self._reject_hook()
                except Exception:  # noqa: BLE001 — stats only
                    pass
            # Retry-After estimate: a slot frees when a live sequence
            # ends or idles out — half the idle-reclaim horizon is the
            # best signal this scheduler has (1s when reclaim is off).
            raise status_map.retryable_error(
                "sequence start for model '%s' rejected: all %d sequence "
                "slots busy and the backlog exceeds max_queue_size %d"
                % (model_name, self._slot_total, self._backlog_max),
                retry_after_s=(self._idle_ns / 2e9 if self._idle_ns
                               else 1.0))
        timeout_ns = self._timeout_ns_for(params)
        deadline_ns = entry_ns + timeout_ns if timeout_ns else 0
        self._backlog += 1
        try:
            while not self._free_slots:
                if cancel is not None and cancel.cancelled():
                    # No slot held yet — backing out of the backlog
                    # (the finally below) is the whole release.
                    cancel.raise_if_cancelled("queue")
                if self._stopping:
                    raise status_map.retryable_error(
                        "server is shutting down", retry_after_s=1.0)
                now = time.monotonic_ns()
                self._reclaim_locked(now)
                if self._free_slots:
                    return
                if deadline_ns and now >= deadline_ns:
                    if self._timeout_hook is not None:
                        try:
                            self._timeout_hook()
                        except Exception:  # noqa: BLE001 — stats only
                            pass
                    raise InferenceServerException(
                        "sequence start for model '%s' timed out after "
                        "%d us waiting for a free sequence slot"
                        % (model_name,
                           (now - entry_ns) // NANOS_PER_US),
                        status="DEADLINE_EXCEEDED")
                if deadline_ns:
                    wait_s = (deadline_ns - now) / 1e9
                elif self._idle_ns:
                    # no deadline: wake for the reaper's next sweep
                    wait_s = self._idle_ns / 1e9
                else:
                    wait_s = None
                self._cv.wait(timeout=wait_s)
        finally:
            self._backlog -= 1

    # -- per-sequence ordering --------------------------------------------

    def _await_turn(self, slot: _Slot, ticket: int, start: bool,
                    cancel=None) -> None:
        with self._cv:
            while slot.serving != ticket:
                if cancel is not None and cancel.cancelled():
                    # Mid-wait this ticket is by definition not the one
                    # being served: abandon it in place and let
                    # _release_turn's turnstile advance skip over it.
                    slot.abandoned.add(ticket)
                    self._cv.notify_all()
                    cancel.raise_if_cancelled("queue")
                if self._stopping:
                    raise status_map.retryable_error(
                        "server is shutting down", retry_after_s=1.0)
                self._cv.wait(timeout=1.0)
            if slot.reclaimed:
                raise _not_started(
                    getattr(self._model, "name", "?"), slot.corrid)
            if slot.ended:
                # The sequence ended while this step waited its turn: a
                # restart step revives the slot, anything else fails.
                if start:
                    slot.ended = False
                else:
                    raise _not_started(
                        getattr(self._model, "name", "?"), slot.corrid)

    def _release_turn(self, slot: _Slot, end: bool) -> None:
        with self._cv:
            slot.serving += 1
            # Skip tickets whose waiter was cancelled mid-wait: nobody
            # will ever claim them, and the next live waiter must not
            # block behind a ghost.
            while slot.serving in slot.abandoned:
                slot.abandoned.discard(slot.serving)
                slot.serving += 1
            slot.last_step_ns = time.monotonic_ns()
            if end:
                slot.ended = True
            if slot.ended and not slot.reclaimed \
                    and slot.serving >= slot.next_ticket:
                # ended with nothing left queued: free the slot (steps
                # still queued behind the end fail/restart in
                # _await_turn, and the last one out frees it here).
                self._free_locked(slot, completed=True)
            self._cv.notify_all()

    def _free_locked(self, slot: _Slot, completed: bool) -> None:
        """Returns the slot to the free pool (caller holds the lock)."""
        live = self._sequences.get(slot.corrid)
        if live is not slot:
            return  # already freed (reclaim/end race)
        del self._sequences[slot.corrid]
        slot.state = {}
        self._free_slots.append(slot.index)
        if completed:
            self._completed_total += 1
        else:
            slot.reclaimed = True
            self._reclaimed_total += 1

    # -- idle reclamation -------------------------------------------------

    def _reclaim_locked(self, now_ns: int) -> None:
        if not self._idle_ns:
            return
        for corrid in list(self._sequences):
            slot = self._sequences[corrid]
            if slot.serving != slot.next_ticket:
                continue  # steps pending or executing: not idle
            if now_ns - slot.last_step_ns >= self._idle_ns:
                self._free_locked(slot, completed=False)

    def _reap_loop(self) -> None:
        interval_s = max(self._idle_ns / 1e9 / 2.0, 0.01)
        with self._cv:
            while not self._stopping:
                before = len(self._free_slots)
                self._reclaim_locked(time.monotonic_ns())
                if len(self._free_slots) != before:
                    self._cv.notify_all()
                # cv.wait (not time.sleep) so stop()'s notify_all wakes
                # the reaper immediately — unload/shutdown must not
                # stall half an idle interval on the join.
                self._cv.wait(timeout=interval_s)

    # -- control + state tensors ------------------------------------------

    def _batched(self, value: np.ndarray, batch: int):
        """Shapes a per-step scalar/row for the model: ``[batch, 1]``
        for batching models (so fused steps stack along the batch dim),
        ``[1]`` otherwise."""
        if int(getattr(self._model, "max_batch_size", 0)) > 0:
            return np.broadcast_to(
                value.reshape(1, -1), (max(batch, 1), value.size)).copy()
        return value

    def _inject_controls(self, exec_inputs: Dict[str, object], batch: int,
                         corrid, start: bool, end: bool) -> None:
        for spec in self._controls:
            np_dtype = triton_to_np_dtype(spec.datatype) or np.int32
            if spec.kind == CONTROL_CORRID:
                try:
                    raw = np.array([int(corrid)], dtype=np_dtype)
                except (TypeError, ValueError, OverflowError):
                    # string correlation ids (and ids outside the
                    # control dtype's range, e.g. a negative id with a
                    # UINT64 control) hash into the corrid slot
                    raw = np.array([hash(str(corrid)) & 0x7FFFFFFF],
                                   dtype=np_dtype)
            elif spec.kind == CONTROL_START:
                raw = np.array([1 if start else 0], dtype=np_dtype)
            elif spec.kind == CONTROL_END:
                raw = np.array([1 if end else 0], dtype=np_dtype)
            else:  # READY: this step is live in its slot
                raw = np.array([1], dtype=np_dtype)
            exec_inputs[spec.name] = self._batched(raw, batch)

    def _initial_state(self, spec: StateSpec, batch: int):
        """Zero state, created ON DEVICE so the whole state lifecycle
        (init -> step N output -> step N+1 input) stays in HBM; numpy
        fallback when no accelerator runtime is importable."""
        dims = tuple(d if d > 0 else 1 for d in spec.dims)
        if int(getattr(self._model, "max_batch_size", 0)) > 0:
            dims = (max(batch, 1),) + dims
        np_dtype = triton_to_np_dtype(spec.datatype) or np.float32
        try:
            import jax.numpy as jnp

            return jnp.zeros(dims, dtype=np_dtype)
        except Exception:  # pragma: no cover — no jax runtime
            return np.zeros(dims, dtype=np_dtype)

    def _attach_state(self, exec_inputs: Dict[str, object], slot: _Slot,
                      batch: int, start: bool) -> None:
        for spec in self._states:
            value = None if start else slot.state.get(spec.input_name)
            if value is None:
                value = self._initial_state(spec, batch)
            exec_inputs[spec.input_name] = value

    def _extract_state(self, outputs: Dict[str, object], slot: _Slot
                       ) -> Dict[str, object]:
        """Pops state outputs from the response and parks them in the
        slot for the next step — WITHOUT materializing to host: a lazy
        device slice of the fused output stays a device array here."""
        remaining = dict(outputs)
        for spec in self._states:
            value = remaining.pop(spec.output_name, None)
            if value is not None:
                slot.state[spec.input_name] = value
        return remaining

    # -- observability ----------------------------------------------------

    def stats_snapshot(self) -> dict:
        with self._cv:
            return {
                "active_sequences": len(self._sequences),
                "slot_total": self._slot_total,
                "backlog_depth": self._backlog,
                "idle_reclaimed_total": self._reclaimed_total,
                "sequences_started": self._started_total,
                "sequences_completed": self._completed_total,
                "step_count": self._step_total,
                "fused_steps": self._fused_step_total,
            }


def _control_specs(model) -> List[ControlSpec]:
    specs = []
    for entry in getattr(model, "sequence_controls", None) or []:
        if isinstance(entry, ControlSpec):
            specs.append(entry)
        else:
            specs.append(ControlSpec(
                entry["name"], entry["kind"],
                entry.get("datatype", "INT32")))
    return specs


def _state_specs(model) -> List[StateSpec]:
    specs = []
    for entry in getattr(model, "sequence_states", None) or []:
        if isinstance(entry, StateSpec):
            specs.append(entry)
        else:
            specs.append(StateSpec(
                entry["input_name"], entry["output_name"],
                entry.get("datatype", "FP32"), entry.get("dims", (1,))))
    return specs


def wants_sequence_batching(model) -> bool:
    return bool(getattr(model, "sequence_batching", False)) \
        and not getattr(model, "decoupled", False)
