"""Server-side shared-memory registry.

Tracks client-registered regions by name, mirroring the role of
triton's shared-memory manager that the reference client talks to via
the Register/Unregister/Status verbs (grpc_client.cc:923-1092):

- **system** regions: POSIX shm segments the server maps read/write.
- **tpu** regions: logical slots in the server-owned HBM arena
  (client_tpu.server.tpu_arena). A slot holds a ``jax.Array``; input
  resolution hands the device array straight to the model and output
  placement swaps the slot's reference — the TPU-native analogue of
  cudaIpcOpenMemHandle'd pointers, with no per-request host copy.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from client_tpu import status_map
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.utils import InferenceServerException
from client_tpu.utils import shared_memory as system_shm


class _SystemRegion:
    kind = "system"

    def __init__(self, name: str, key: str, offset: int, byte_size: int,
                 handle: system_shm.SharedMemoryRegion):
        self.name = name
        self.key = key
        self.offset = offset
        self.byte_size = byte_size
        self.handle = handle


class _TpuRegion:
    kind = "tpu"

    def __init__(self, name: str, region_id: str, device_id: int,
                 byte_size: int, pulled: bool = False):
        self.name = name
        self.region_id = region_id
        self.device_id = device_id
        self.byte_size = byte_size
        # True when the region is a local replica the server pulled
        # over DCN from another host's arena: the server owns it, so
        # unregistration destroys it (nobody else holds its handle).
        self.pulled = pulled


class SharedMemoryManager:
    """Name -> region registry + data plane resolution."""

    def __init__(self, tpu_arena=None):
        self._lock = threading.Lock()
        self._system: Dict[str, _SystemRegion] = {}
        self._tpu: Dict[str, _TpuRegion] = {}
        self._arena = tpu_arena

    @property
    def arena(self):
        return self._arena

    # -- registration verbs ---------------------------------------------

    def register_system(self, name: str, key: str, offset: int,
                        byte_size: int) -> None:
        with self._lock:
            if name in self._system or name in self._tpu:
                raise InferenceServerException(
                    "shared memory region '%s' already registered" % name,
                    status="ALREADY_EXISTS",
                )
            try:
                handle = system_shm.attach_shared_memory_region(
                    name, key, offset + byte_size
                )
            except system_shm.SharedMemoryException as e:
                raise InferenceServerException(str(e), status="INVALID_ARGUMENT")
            self._system[name] = _SystemRegion(name, key, offset, byte_size, handle)

    def unregister_system(self, name: str) -> None:
        with self._lock:
            if not name:  # empty name = unregister all (v2 convention)
                for region in self._system.values():
                    system_shm.detach_shared_memory_region(region.handle)
                self._system.clear()
                return
            region = self._system.pop(name, None)
            if region is not None:
                system_shm.detach_shared_memory_region(region.handle)

    def system_status(self, name: str = "") -> pb.SystemSharedMemoryStatusResponse:
        response = pb.SystemSharedMemoryStatusResponse()
        with self._lock:
            regions = (
                [self._system[name]] if name and name in self._system
                else ([] if name else list(self._system.values()))
            )
            for r in regions:
                response.regions[r.name].name = r.name
                response.regions[r.name].key = r.key
                response.regions[r.name].offset = r.offset
                response.regions[r.name].byte_size = r.byte_size
        return response

    def register_tpu(self, name: str, raw_handle: bytes, device_id: int,
                     byte_size: int) -> None:
        if self._arena is None:
            # UNAVAILABLE for wire parity with the reference; the
            # condition only clears on an operator restart with an
            # arena configured, so advertise a long re-probe interval.
            raise status_map.retryable_error(
                "server has no TPU arena; TPU shared memory unavailable",
                retry_after_s=30.0,
            )
        with self._lock:
            if name in self._system or name in self._tpu:
                raise InferenceServerException(
                    "shared memory region '%s' already registered" % name,
                    status="ALREADY_EXISTS",
                )
            try:
                region_id = self._arena.validate_handle(
                    raw_handle, device_id, byte_size)
                self._tpu[name] = _TpuRegion(name, region_id, device_id,
                                             byte_size)
                return
            except InferenceServerException:
                from client_tpu.server.arena_pull import foreign_owner_url

                owner = foreign_owner_url(raw_handle, self._arena.arena_id)
                if owner is None:
                    raise
        # Foreign handle with routing info: redeem it over the DCN pull
        # path (docs/cross_host_arena.md rule 2) — stream the owner's
        # typed segments into a local replica, then serve locally. The
        # pull runs OUTSIDE the registry lock (a cross-host transfer
        # must not block unrelated registrations).
        import json

        from client_tpu.server.arena_pull import pull_region

        # Reject an oversized registration BEFORE paying the DCN
        # transfer: the owner's descriptor carries the region size.
        try:
            claimed = int(json.loads(raw_handle).get("byte_size", 0))
        except (ValueError, TypeError):
            claimed = 0
        if claimed and byte_size > claimed:
            raise InferenceServerException(
                "registered byte_size %d exceeds region size %d"
                % (byte_size, claimed), status="INVALID_ARGUMENT")
        local_handle = pull_region(owner, raw_handle, self._arena)
        descriptor = json.loads(local_handle)
        local_device = descriptor["device_id"]
        try:
            with self._lock:
                if name in self._system or name in self._tpu:
                    raise InferenceServerException(
                        "shared memory region '%s' already registered" % name,
                        status="ALREADY_EXISTS",
                    )
                region_id = self._arena.validate_handle(
                    local_handle, local_device, byte_size)
                self._tpu[name] = _TpuRegion(name, region_id, local_device,
                                             byte_size, pulled=True)
        except Exception:
            # Any post-pull failure: the replica has no name and no
            # handle holder — free its HBM instead of leaking it.
            self._arena.destroy_region(descriptor["region_id"])
            raise

    def unregister_tpu(self, name: str) -> None:
        with self._lock:
            if not name:
                pulled = [r for r in self._tpu.values() if r.pulled]
                self._tpu.clear()
            else:
                region = self._tpu.pop(name, None)
                pulled = [region] if region is not None and region.pulled \
                    else []
        # Pulled replicas are server-owned: free their HBM now (outside
        # the lock; destroy only drops references).
        for region in pulled:
            if self._arena is not None:
                self._arena.destroy_region(region.region_id)

    def tpu_status(self, name: str = "") -> pb.TpuSharedMemoryStatusResponse:
        response = pb.TpuSharedMemoryStatusResponse()
        with self._lock:
            regions = (
                [self._tpu[name]] if name and name in self._tpu
                else ([] if name else list(self._tpu.values()))
            )
            for r in regions:
                response.regions[r.name].name = r.name
                response.regions[r.name].device_id = r.device_id
                response.regions[r.name].byte_size = r.byte_size
        return response

    # -- data plane ------------------------------------------------------

    def _get(self, name: str):
        with self._lock:
            region = self._system.get(name) or self._tpu.get(name)
        if region is None:
            raise InferenceServerException(
                "shared memory region '%s' is not registered" % name,
                status="NOT_FOUND",
            )
        return region

    def read_input(self, name: str, byte_size: int, offset: int,
                   datatype: str, shape):
        """Resolve a shm-referenced input to an array the model can
        consume: numpy view for system regions, device ``jax.Array``
        for TPU regions (no host round-trip)."""
        region = self._get(name)
        if region.kind == "system":
            if offset + byte_size > region.byte_size:
                raise InferenceServerException(
                    "input exceeds region '%s' bounds" % name,
                    status="INVALID_ARGUMENT",
                )
            buf = region.handle.buf()
            base = region.offset + offset
            return _bytes_to_array(
                memoryview(buf)[base : base + byte_size], datatype, shape
            )
        return self._arena.as_typed_array(
            region.region_id, offset, byte_size, datatype, shape
        )

    def write_output(self, name: str, byte_size: int, offset: int, value) -> int:
        """Place an output tensor into a region. Returns bytes written.
        TPU regions store the device array by reference (zero copy);
        system regions take the fetch-into-region path
        (client_tpu.server.fetch.fetch_into): the old chain was host
        ndarray -> whole-buffer bytes object -> region copy; the bytes
        hop is retired, so numeric tensors cost one host
        materialization (a zero-copy view for cpu-committed jax
        arrays) plus the copy into the region. BYTES tensors keep the
        serialize path (variable-length framing has no flat byte
        view)."""
        region = self._get(name)
        if region.kind == "system":
            nbytes = _tensor_nbytes(value)
            if nbytes is None:
                # BYTES / unknown layout: legacy serialize-then-copy.
                data = _array_to_bytes(value)
                nbytes = len(data)
            else:
                data = None
            if nbytes > byte_size:
                raise InferenceServerException(
                    "output of %d bytes exceeds the requested %d-byte slice "
                    "of region '%s'" % (nbytes, byte_size, name),
                    status="INVALID_ARGUMENT",
                )
            if offset + nbytes > region.byte_size:
                raise InferenceServerException(
                    "output exceeds region '%s' bounds (%d > %d)"
                    % (name, offset + nbytes, region.byte_size),
                    status="INVALID_ARGUMENT",
                )
            buf = region.handle.buf()
            base = region.offset + offset
            if data is not None:
                buf[base : base + nbytes] = data
            else:
                from client_tpu.server.fetch import fetch_into

                fetch_into(value, memoryview(buf)[base : base + nbytes])
            return nbytes
        return self._arena.store(region.region_id, offset, byte_size, value)


def _bytes_to_array(view, datatype: str, shape):
    from client_tpu.utils import (
        deserialize_bf16_tensor,
        deserialize_bytes_tensor,
        triton_to_np_dtype,
    )

    if datatype == "BYTES":
        return deserialize_bytes_tensor(bytes(view)).reshape(shape)
    if datatype == "BF16":
        return deserialize_bf16_tensor(bytes(view)).reshape(shape)
    return np.frombuffer(view, dtype=triton_to_np_dtype(datatype)).reshape(shape)


def _tensor_nbytes(value):
    """Byte size of a numeric tensor from its METADATA (device arrays
    carry dtype/shape without a host trip), or None when the tensor
    needs serialization (BYTES/string) or has no dtype at all."""
    dtype = getattr(value, "dtype", None)
    shape = getattr(value, "shape", None)
    if dtype is None or shape is None:
        return None
    dtype = np.dtype(dtype)
    if dtype.kind in ("O", "S", "U"):
        return None
    return int(np.prod(shape)) * dtype.itemsize


def _array_to_bytes(value) -> bytes:
    from client_tpu.utils import serialize_byte_tensor

    arr = np.asarray(value)
    if arr.dtype.kind in ("O", "S", "U"):
        return serialize_byte_tensor(arr).tobytes()
    return np.ascontiguousarray(arr).tobytes()
