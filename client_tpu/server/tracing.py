"""Server-side span recorder: per-request timelines across every
serving stage.

The Dapper-style (Sigelman et al., 2010) replacement for the old flat
t0-t3 trace record: a sampled request carries a :class:`RequestTrace`
through the core, cache, sequence scheduler, and dynamic batcher, and
each stage records a :class:`Span` — monotonic-ns bounds, a parent
link, and a small attribute dict. Stages that serve several requests
with ONE piece of work (a fused batch execution, the batched relay
fetch) record a *shared* span: the same span id appears in every
member request's trace, so a reader can both attribute the time to
each request and recognize the work was done once.

Design constraints:

* **Near-zero cost when sampled out.** An unsampled request carries
  ``trace=None`` and every instrumentation point is a single ``is
  None`` check — no allocation, no clock read, no lock.
* **Thread-safe per trace.** The request thread records decode/encode
  while scheduler pool threads record queue/execute/fetch; appends
  take the trace's own lock (uncontended in practice — the request
  thread is parked on an event while pool threads run).
* **Transport-joinable.** A trace created with a W3C ``traceparent``
  (client_tpu.tracing) adopts the caller's trace id and parents its
  root span under the client span, so client and server spans form
  one tree.

Export formats (the ``trace_mode`` setting, rendered by
:func:`compact_record` / :func:`chrome_events`):

* ``compact`` — one JSON line per request: spans + the legacy
  five-point ``timestamps`` list (REQUEST_START..REQUEST_END), so
  pre-span consumers keep working.
* ``chrome`` — Chrome trace / Perfetto "X" (complete) events, one
  request per tid; open the file in https://ui.perfetto.dev.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from client_tpu.tracing import (  # noqa: F401 — re-exported for servers
    TRACEPARENT_HEADER,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)

TRACE_MODES = ("compact", "chrome")

# Span names are the stable contract the perf harness's stage
# attribution maps on (client_tpu.perf.report.STAGE_SPANS); add new
# stages there too or they land in the "other" bucket.
SPAN_REQUEST = "request"
SPAN_DECODE = "decode"
SPAN_CACHE_LOOKUP = "cache_lookup"
SPAN_CACHE_WAIT = "cache_wait"
SPAN_CACHE_INSERT = "cache_insert"
SPAN_QUEUE = "queue"
SPAN_SEQUENCE_WAIT = "sequence_slot_wait"
SPAN_BATCH_EXECUTE = "batch_execute"
SPAN_DEVICE_EXECUTE = "device_execute"
SPAN_RELAY_FETCH = "relay_fetch"
SPAN_ENCODE = "encode"
SPAN_STREAM_RESPONSE = "stream_response"
SPAN_ENSEMBLE_STEP = "ensemble_step"


class Span:
    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "attrs")

    def __init__(self, name: str, span_id: str, parent_id: Optional[str],
                 start_ns: int, end_ns: int = 0,
                 attrs: Optional[dict] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.attrs = attrs

    def as_dict(self) -> dict:
        out = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_span_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
        }
        if self.attrs:
            out["attrs"] = self.attrs
        return out


def exemplar_id(trace: Optional["RequestTrace"]) -> Optional[str]:
    """The trace id a telemetry observation may stamp as an
    OpenMetrics exemplar: only SAMPLED traces qualify — a flight
    scratch trace is usually discarded, and an exemplar pointing at a
    trace that exists nowhere is worse than none."""
    if trace is None or not trace.sampled:
        return None
    return trace.trace_id


def shared_span(name: str, start_ns: int, end_ns: int,
                attrs: Optional[dict] = None) -> Span:
    """A span representing work shared by several requests (fused
    batch execute, batched relay fetch). It has no parent — each
    member trace records it at top level with ``shared: true`` so
    tree readers treat it as a link, not a child."""
    attrs = dict(attrs) if attrs else {}
    attrs["shared"] = True
    return Span(name, new_span_id(), None, start_ns, end_ns, attrs)


class RequestTrace:
    """One request's span tree (plus bookkeeping the core needs at
    emit time). ``sampled=False`` marks a flight-recorder scratch
    trace (client_tpu.server.flight): captured for every request but
    usually discarded at completion — such traces must NOT stamp
    OpenMetrics exemplars, or discarded scratch ids would overwrite
    the sampled-trace ids the exemplar->span-tree join depends on."""

    __slots__ = ("trace_id", "parent_span_id", "root", "spans", "_lock",
                 "timeline", "sampled")

    def __init__(self, trace_context: Optional[str] = None,
                 attrs: Optional[dict] = None, sampled: bool = True):
        parsed = parse_traceparent(trace_context)
        if parsed is not None:
            self.trace_id, self.parent_span_id = parsed
        else:
            self.trace_id, self.parent_span_id = new_trace_id(), None
        self.root = Span(SPAN_REQUEST, new_span_id(), self.parent_span_id,
                         time.monotonic_ns(), attrs=attrs or {})
        self.spans: List[Span] = []
        self.sampled = bool(sampled)
        self._lock = threading.Lock()
        # Optional legacy five-point timeline (t0, queue_start,
        # compute_start, compute_end, t3) set by the executed path;
        # emit falls back to the root bounds when absent.
        self.timeline = None

    # -- recording --------------------------------------------------------

    def begin(self, name: str, parent: Optional[Span] = None,
              attrs: Optional[dict] = None) -> Span:
        """Starts a span (child of the root unless ``parent`` given).
        The span is recorded at END time so readers never see
        half-open spans."""
        parent_id = (parent or self.root).span_id
        return Span(name, new_span_id(), parent_id, time.monotonic_ns(),
                    attrs=attrs)

    def end(self, span: Span, attrs: Optional[dict] = None) -> Span:
        span.end_ns = time.monotonic_ns()
        if attrs:
            span.attrs = dict(span.attrs or {})
            span.attrs.update(attrs)
        self.add(span)
        return span

    def add(self, span: Span) -> None:
        """Records a finished span (also the entry point for shared
        spans built by the batcher)."""
        with self._lock:
            self.spans.append(span)

    def add_timed(self, name: str, start_ns: int, end_ns: int,
                  attrs: Optional[dict] = None) -> Span:
        """Records a span from explicit bounds (for stages timed with
        existing counters, e.g. the batcher's queue wait)."""
        span = Span(name, new_span_id(), self.root.span_id, start_ns,
                    end_ns, attrs)
        self.add(span)
        return span

    def finish(self, error: Optional[str] = None) -> None:
        """Closes the root span. On success the root ends where the
        LAST recorded span ends — the post-span slice is only stack
        unwind, stats bookkeeping, and scheduler wake noise, and
        counting it would make every stage table read "x% unattributed
        overhead" on contended hosts (the client-visible tail is the
        harness's latency percentiles' job). Failed requests keep a
        fresh clock read: the path to the failure point is exactly
        what their root must cover."""
        with self._lock:
            last_ns = max((s.end_ns for s in self.spans), default=0)
        if error or not last_ns:
            self.root.end_ns = time.monotonic_ns()
        else:
            self.root.end_ns = max(last_ns, self.root.start_ns)
        if error:
            self.root.attrs = dict(self.root.attrs or {})
            self.root.attrs["error"] = error

    def snapshot(self) -> List[Span]:
        with self._lock:
            return [self.root] + list(self.spans)


# -- rendering ------------------------------------------------------------


def _legacy_timestamps(trace: RequestTrace) -> List[dict]:
    """The pre-span five-point timeline, derived from the explicit
    timeline when the executed path recorded one, else degenerate at
    the root bounds (cache hits never queue or compute)."""
    if trace.timeline is not None:
        t0, t_queue, t_compute, t_end_compute, t3 = trace.timeline
    else:
        t0 = t_queue = t_compute = t_end_compute = trace.root.start_ns
        t3 = trace.root.end_ns or t0
    return [
        {"name": "REQUEST_START", "ns": t0},
        {"name": "QUEUE_START", "ns": t_queue},
        {"name": "COMPUTE_START", "ns": t_compute},
        {"name": "COMPUTE_END", "ns": t_end_compute},
        {"name": "REQUEST_END", "ns": t3},
    ]


def compact_record(trace: RequestTrace, record_id: int, model_name: str,
                   request_id: str) -> dict:
    """One JSON-able record per request for ``trace_mode=compact``."""
    return {
        "id": record_id,
        "model_name": model_name,
        "request_id": request_id,
        "trace_id": trace.trace_id,
        "parent_span_id": trace.parent_span_id,
        "timestamps": _legacy_timestamps(trace),
        "spans": [span.as_dict() for span in trace.snapshot()],
    }


def chrome_span_events(spans: List[dict], model_name: str, tid: int,
                       thread_label: str,
                       common_args: dict) -> List[dict]:
    """Chrome-trace complete ("X") events from span DICTS
    (``Span.as_dict`` form) — the ONE event builder shared by the
    trace buffers (:func:`chrome_events`) and the flight recorder's
    ring export, so the two can never drift to incompatible layouts.
    One pid per model, one tid per record; ts/dur are microseconds
    (floats keep sub-us spans visible in Perfetto). The pid is a
    stable digest — builtin hash() is salted per process, which would
    scatter one model across pids between runs."""
    import zlib

    pid = zlib.crc32(model_name.encode()) % 100000
    events: List[dict] = [{
        "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
        "args": {"name": thread_label},
    }, {
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": "model %s" % model_name},
    }]
    for span in spans:
        start_ns = int(span.get("start_ns", 0))
        end_ns = int(span.get("end_ns", 0)) or start_ns
        event = {
            "name": span.get("name"),
            "ph": "X",
            "pid": pid,
            "tid": tid,
            "ts": start_ns / 1000.0,
            "dur": max(end_ns - start_ns, 0) / 1000.0,
            "args": {
                "span_id": span.get("span_id"),
                "parent_span_id": span.get("parent_span_id"),
            },
        }
        event["args"].update(common_args)
        if span.get("attrs"):
            event["args"].update(span["attrs"])
        events.append(event)
    return events


def chrome_events(trace: RequestTrace, record_id: int, model_name: str,
                  request_id: str) -> List[dict]:
    """Chrome-trace events for ``trace_mode=chrome`` (one sampled
    request's tree; rendering via :func:`chrome_span_events`)."""
    return chrome_span_events(
        [span.as_dict() for span in trace.snapshot()],
        model_name, record_id,
        "req %s %s" % (request_id, trace.trace_id[:8]),
        {"trace_id": trace.trace_id, "request_id": request_id})


# -- stage attribution ----------------------------------------------------

def stage_durations(spans: List[dict],
                    stage_map: Dict[str, str]) -> Dict[str, int]:
    """Sums span durations (ns) into stages per ``stage_map``
    ({span_name: stage}); unmapped non-root spans land in "other".
    Shared spans count fully toward each member request (attribution
    view, not a work count)."""
    out: Dict[str, int] = {}
    for span in spans:
        name = span.get("name", "")
        if name == SPAN_REQUEST:
            continue
        stage = stage_map.get(name, "other")
        duration = max(
            int(span.get("end_ns", 0)) - int(span.get("start_ns", 0)), 0)
        out[stage] = out.get(stage, 0) + duration
    return out
