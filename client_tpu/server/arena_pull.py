"""Cross-host (DCN) arena pull path.

Consumer side of `docs/cross_host_arena.md` rule 2: when a request
lands on host A but its shm-referenced tensor lives in host B's arena,
A *pulls* — streams B's typed segments over the arena service and
`device_put`s them into its own arena, then serves locally.

Design points (vs the old ReadRegion byte copy):

- **Typed, not a blob**: segment metadata (offset/dtype/shape) rides
  with the bytes, so the pulled region reproduces the owner's typed
  layout and the zero-copy `as_typed_array` fast path works on the
  consumer exactly as on the owner.
- **No whole-region host bounce on the consumer**: each network chunk
  is `device_put` as it arrives; assembly (concatenate + bitcast to
  the segment dtype) happens on the consumer's device. Host memory
  holds at most one chunk at a time per segment.
- **The handle is the capability**: the owner authenticates the full
  descriptor (arena_id + region + nonce) before any byte leaves it.

The reference's zero-copy contract this replaces:
`src/c++/perf_analyzer/infer_data_manager_shm.h:56` (CUDA-IPC regions
shared by address); CUDA IPC cannot cross hosts at all — the pull path
is the TPU-native extension of the same handle-redemption model to a
DCN-connected fleet.
"""

from __future__ import annotations

import json
from typing import Iterator, Optional

import numpy as np

from client_tpu import status_map
from client_tpu.protocol import arena_pb2
from client_tpu.server.tpu_arena import TpuArena
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
    wire_dtype_element_size,
)

DEFAULT_CHUNK_BYTES = 2 * 1024 * 1024


# -- owner side -----------------------------------------------------------

def iter_region_chunks(arena: TpuArena, raw_handle: bytes,
                       chunk_bytes: int = 0
                       ) -> Iterator[arena_pb2.PullRegionChunk]:
    """Stream a region's segments as PullRegionChunk messages.

    Serialization happens per segment AFTER the snapshot (segment
    arrays are immutable), so the owner never holds its region lock
    across a device->host transfer or a network send."""
    region = arena.resolve_pull_handle(raw_handle)
    chunk_bytes = chunk_bytes or DEFAULT_CHUNK_BYTES
    segments = arena.snapshot_segments(region.region_id)
    first = True

    def stamp(msg):
        nonlocal first
        if first:
            msg.region_byte_size = region.byte_size
            msg.device_id = region.device_id
            first = False
        return msg

    if not segments:
        # Empty region: one metadata-only chunk so the consumer can
        # still size and create its local region.
        yield stamp(arena_pb2.PullRegionChunk(segment_nbytes=0))
        return
    for index, segment in enumerate(segments):
        # One host materialization per segment, chunked by slicing the
        # byte view: each proto chunk copies once (into the message),
        # never via an intermediate whole-segment bytes object.
        raw = TpuArena._segment_view(segment)
        position = 0
        while True:
            data = bytes(raw[position:position + chunk_bytes])
            yield stamp(arena_pb2.PullRegionChunk(
                segment_index=index,
                segment_offset=segment.offset,
                segment_nbytes=len(raw),
                datatype=segment.datatype or "",
                shape=segment.shape or [],
                chunk_offset=position,
                data=data,
            ))
            position += len(data)
            if position >= len(raw):
                break


# -- consumer side --------------------------------------------------------

def _typed_from_u8(jax, flat_u8, datatype: str, shape):
    """Reinterpret a flat uint8 device array as datatype/shape on
    device (mirrors TpuArena.as_typed_array's bitcast path)."""
    import jax.numpy as jnp

    if datatype == "BOOL":
        return flat_u8.astype(jnp.bool_).reshape(shape)
    elem = wire_dtype_element_size(datatype)
    np_dtype = triton_to_np_dtype(datatype)
    typed = jax.lax.bitcast_convert_type(
        flat_u8.reshape(-1, elem), jnp.dtype(np_dtype))
    return typed.reshape(shape)


class _PendingSegment:
    """One in-flight segment: network chunks are device_put as they
    arrive; the typed assembly happens on device at flush."""

    def __init__(self, msg):
        self.index = msg.segment_index
        self.offset = int(msg.segment_offset)
        self.nbytes = int(msg.segment_nbytes)
        self.datatype = msg.datatype
        self.shape = list(msg.shape)
        self.parts: list = []      # device u8 chunks (non-BYTES)
        self.host_parts: list = [] # host bytes (BYTES stays host-side)
        self.received = 0

    def add(self, jax, device, msg) -> None:
        if int(msg.chunk_offset) != self.received:
            raise InferenceServerException(
                "pull stream out of order (segment %d: chunk at %d, "
                "expected %d)" % (self.index, msg.chunk_offset,
                                  self.received),
                status="INTERNAL")
        if self.datatype == "BYTES":
            self.host_parts.append(msg.data)
        else:
            self.parts.append(jax.device_put(
                np.frombuffer(msg.data, np.uint8), device))
        self.received += len(msg.data)

    def flush(self, jax, arena: TpuArena, region_id: str) -> None:
        import jax.numpy as jnp

        if self.received != self.nbytes:
            raise InferenceServerException(
                "pull stream truncated (segment %d: %d of %d bytes)"
                % (self.index, self.received, self.nbytes),
                status="INTERNAL")
        if self.datatype == "BYTES":
            raw = b"".join(self.host_parts)
            array = deserialize_bytes_tensor(raw)
            if self.shape:
                array = array.reshape(self.shape)
            arena.adopt_segment(region_id, self.offset, self.nbytes,
                                "BYTES", self.shape, array)
            return
        flat = (self.parts[0] if len(self.parts) == 1
                else jnp.concatenate(self.parts))
        if self.datatype:
            array = _typed_from_u8(jax, flat, self.datatype, self.shape)
            arena.adopt_segment(region_id, self.offset, self.nbytes,
                                self.datatype, self.shape, array)
        else:
            arena.adopt_segment(region_id, self.offset, self.nbytes,
                                None, None, flat)


DEFAULT_PULL_TIMEOUT_S = 120.0


def pull_region(owner, raw_handle: bytes, local_arena: TpuArena,
                device_id: Optional[int] = None,
                chunk_bytes: int = 0,
                timeout_s: float = DEFAULT_PULL_TIMEOUT_S) -> bytes:
    """Redeem a foreign region handle: stream the owner's segments into
    a fresh region of ``local_arena`` and return the LOCAL handle.

    ``owner`` is the owner's address ("host:port"), an open grpc
    channel, or a TpuArenaStub. ``device_id`` pins the local placement
    (default: the owner's device_id when locally valid, else 0).
    ``timeout_s`` bounds the whole stream — a partitioned owner must
    fail the redemption, not pin the consumer's registration thread."""
    import grpc

    from client_tpu.server.arena_service import TpuArenaStub

    jax = local_arena._jax
    own_channel = None
    if isinstance(owner, str):
        own_channel = grpc.insecure_channel(owner)
        stub = TpuArenaStub(own_channel)
    elif hasattr(owner, "PullRegion"):
        stub = owner
    else:
        stub = TpuArenaStub(owner)
    local_handle = None
    region_id = None
    try:
        stream = stub.PullRegion(
            arena_pb2.PullRegionRequest(
                raw_handle=raw_handle, chunk_bytes=chunk_bytes),
            timeout=timeout_s or None)
        device = None
        pending: Optional[_PendingSegment] = None
        for msg in stream:
            if local_handle is None:
                size = int(msg.region_byte_size)
                if size <= 0:
                    raise InferenceServerException(
                        "pull stream missing region size",
                        status="INTERNAL")
                if device_id is None:
                    owner_dev = int(msg.device_id)
                    device_id = (owner_dev if 0 <= owner_dev
                                 < len(local_arena._devices) else 0)
                local_handle = local_arena.create_region(size, device_id)
                region_id = json.loads(local_handle)["region_id"]
                device = local_arena.device_for(device_id)
            if msg.segment_nbytes == 0:
                continue  # empty-region marker
            if pending is not None and msg.segment_index != pending.index:
                pending.flush(jax, local_arena, region_id)
                pending = None
            if pending is None:
                pending = _PendingSegment(msg)
            pending.add(jax, device, msg)
        if local_handle is None:
            raise InferenceServerException(
                "owner sent an empty pull stream", status="INTERNAL")
        if pending is not None:
            pending.flush(jax, local_arena, region_id)
        handle = local_handle
        local_handle = None  # success: skip the cleanup below
        return handle
    except grpc.RpcError as err:
        # Preserve the owner's verdict: NOT_FOUND/INVALID_ARGUMENT are
        # permanent (a retry loop keyed on UNAVAILABLE must not spin on
        # a dead handle); everything else is a transport failure.
        code = err.code() if hasattr(err, "code") else None
        status = status_map.status_of_grpc_code(code)
        if status not in ("NOT_FOUND", "INVALID_ARGUMENT"):
            status = "UNAVAILABLE"
        raise InferenceServerException(
            "DCN pull from region owner failed: %s"
            % getattr(err, "details", lambda: err)(),
            status=status)
    finally:
        if local_handle is not None and region_id is not None:
            local_arena.destroy_region(region_id)  # failed pull: no leak
        if own_channel is not None:
            own_channel.close()


def resolve_arena_route(bound: str) -> str:
    """The single routing policy every front-end applies post-bind:
    CLIENT_TPU_ARENA_URL wins unconditionally (the operator's explicit
    route for NAT'd deployments); otherwise the bound address routes
    unless its host is a bind-any address (0.0.0.0 is where to listen,
    not where to be reached). Returns "" for 'publish nothing'."""
    import os

    env = os.environ.get("CLIENT_TPU_ARENA_URL")
    if env:
        return env
    host = bound.rsplit(":", 1)[0] if bound else ""
    return "" if host in ("0.0.0.0", "[::]", "") else bound


def foreign_owner_url(raw_handle: bytes, local_arena_id: str
                      ) -> Optional[str]:
    """The owner's address when ``raw_handle`` belongs to ANOTHER
    host's arena and carries routing info; None for local or
    unroutable handles."""
    try:
        descriptor = json.loads(raw_handle)
    except (json.JSONDecodeError, UnicodeDecodeError, TypeError):
        return None
    if descriptor.get("arena_id") == local_arena_id:
        return None
    return descriptor.get("owner_url") or None
