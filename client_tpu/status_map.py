"""The one canonical status mapping table.

Every translation between canonical status strings (the
``InferenceServerException.status()`` vocabulary, which matches
``grpc.StatusCode`` member names) and wire codes (HTTP ints, gRPC
codes) lives here. Before this module the same tables were hand-copied
into three front-ends and two clients and drifted; tpulint's
``status-literal`` checker now fails any new shadow table or bare
status literal outside this file.

Retry-After policy also lives here: every ``UNAVAILABLE`` /
``RESOURCE_EXHAUSTED`` error a server component raises must carry a
``retry_after_s`` estimate (construct it via :func:`retryable_error`);
the front-ends serialize it as the HTTP ``Retry-After`` header
(integer delta-seconds, RFC 9110) and the gRPC ``retry-after``
trailing metadata (sub-second precision). tpulint's ``retry-after``
checker enforces the construction side.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from client_tpu.utils import InferenceServerException

# Canonical status string -> HTTP response code. Statuses absent from
# the table (UNKNOWN, transport noise) fall back to HTTP_INTERNAL —
# the pre-refactor behavior of every front-end copy. CANCELLED maps to
# 499 (nginx's "client closed request"): the caller is gone, so the
# code is for proxies and access logs, not the client.
HTTP_STATUS: Dict[str, int] = {
    "NOT_FOUND": 404,
    "INVALID_ARGUMENT": 400,
    "ALREADY_EXISTS": 409,
    "UNAVAILABLE": 503,
    "DEADLINE_EXCEEDED": 504,
    "RESOURCE_EXHAUSTED": 429,
    "UNIMPLEMENTED": 501,
    "INTERNAL": 500,
    "PERMISSION_DENIED": 403,
    "UNAUTHENTICATED": 401,
    "CANCELLED": 499,
}

HTTP_OK = 200
HTTP_BAD_REQUEST = 400
HTTP_NOT_FOUND = 404
HTTP_INTERNAL = 500
#: First HTTP status code that is an error (RFC 9110 client errors).
HTTP_ERROR_FLOOR = 400

#: Statuses a well-behaved client may retry (the server sheds with an
#: honest Retry-After; see retryable_error). Canonical + HTTP string
#: forms, because client-side errors carry whichever the transport saw.
RETRYABLE_STATUSES = frozenset({"UNAVAILABLE", "RESOURCE_EXHAUSTED"})
RETRYABLE_HTTP = frozenset({503, 429})
DEFAULT_RETRYABLE_WIRE = ("UNAVAILABLE", "503", "RESOURCE_EXHAUSTED", "429")

#: Per-tenant quota rejects: retryable but POLICY signals, not
#: availability evidence (client breakers must not count them).
QUOTA_REJECT_WIRE = frozenset({"RESOURCE_EXHAUSTED", "429"})

#: Flight-recorder keep reasons for statuses with a dedicated
#: retention label (client_tpu/server/flight.py); any other failed
#: status keeps under the generic "error" reason.
FLIGHT_KEEP_REASONS = {
    "DEADLINE_EXCEEDED": "timeout",
    "UNAVAILABLE": "shed",
    "RESOURCE_EXHAUSTED": "quota",
    "CANCELLED": "cancelled",
}

#: Definitive client errors — the server answered decisively, which is
#: proof of health, not failure (client breakers count them as
#: successes). Canonical + HTTP string forms.
CLIENT_ERROR_WIRE = frozenset({
    "INVALID_ARGUMENT", "400", "NOT_FOUND", "404", "ALREADY_EXISTS",
    "409", "UNIMPLEMENTED", "501", "PERMISSION_DENIED", "403",
    "UNAUTHENTICATED", "401",
})


def http_status(status: Optional[str]) -> int:
    """Canonical status string (or None) -> HTTP response code."""
    return HTTP_STATUS.get(status or "", HTTP_INTERNAL)


def grpc_code(status: Optional[str]):
    """Canonical status string (or None) -> ``grpc.StatusCode``.

    grpc is imported lazily: HTTP-only deployments never pay for it."""
    import grpc

    try:
        return grpc.StatusCode[status or "INTERNAL"]
    except KeyError:
        return grpc.StatusCode.INTERNAL


def status_of_grpc_code(code) -> Optional[str]:
    """``grpc.StatusCode`` (or None) -> canonical status string."""
    return getattr(code, "name", None)


def is_retryable_status(status: Optional[str]) -> bool:
    return (status or "") in RETRYABLE_STATUSES


def retryable_error(msg: str, status: str = "UNAVAILABLE",
                    retry_after_s: float = 1.0,
                    debug_details=None) -> InferenceServerException:
    """An UNAVAILABLE/RESOURCE_EXHAUSTED error with its Retry-After
    estimate attached — the only sanctioned way to construct one.
    ``retry_after_s`` is the server's honest guess at when capacity
    returns (queue-drain estimate, token-bucket refill, supervisor
    recovery interval); it is floored at 1 ms so a zero can never
    serialize as "don't wait"."""
    assert status in RETRYABLE_STATUSES, status
    error = InferenceServerException(msg, status=status,
                                     debug_details=debug_details)
    error.retry_after_s = max(float(retry_after_s), 0.001)
    return error


def retry_after_headers(code: int, error: BaseException,
                        headers: Optional[dict] = None) -> Optional[dict]:
    """Adds the ``Retry-After`` header for shed/quota responses.

    The value is the error's server-computed estimate when present,
    else the legacy 1 — rounded UP to whole seconds: RFC 9110
    delta-seconds is integer, and third-party consumers (urllib3,
    proxies) fail a float parse. The gRPC trailing metadata keeps
    sub-second precision."""
    if code not in RETRYABLE_HTTP:
        return headers
    retry_after = getattr(error, "retry_after_s", None)
    value = "%d" % max(math.ceil(retry_after), 1) if retry_after else "1"
    headers = dict(headers) if headers else {}
    headers["Retry-After"] = value
    return headers
