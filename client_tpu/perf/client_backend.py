"""Pluggable client-backend abstraction for the perf harness.

Mirrors the role of cb::ClientBackend (/root/reference/src/c++/
perf_analyzer/client_backend/client_backend.h:366): the load
generators talk to this interface, concrete backends adapt it to the
gRPC client, the HTTP client, or the in-process server core (the
analogue of the TRITONSERVER C-API backend, triton_c_api/).
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu.utils import InferenceServerException


class BackendKind(enum.Enum):
    TRITON_GRPC = "grpc"
    TRITON_HTTP = "http"
    IN_PROCESS = "inprocess"
    OPENAI = "openai"
    TORCHSERVE = "torchserve"
    TFSERVING = "tfserving"
    MOCK = "mock"


class ClientBackend:
    """One backend instance per worker thread (like the reference,
    where each worker owns a client)."""

    kind: BackendKind

    # control-plane ------------------------------------------------------
    def server_metadata(self):
        raise NotImplementedError

    def model_metadata(self, model_name: str, model_version: str = ""):
        raise NotImplementedError

    def model_config(self, model_name: str, model_version: str = ""):
        raise NotImplementedError

    def model_statistics(self, model_name: str = "", model_version: str = ""):
        raise NotImplementedError

    def update_trace_settings(self, model_name: str = "", settings=None):
        """Server-side trace settings (the harness's --trace wiring);
        backends without a trace surface raise."""
        raise InferenceServerException(
            "%s does not support trace settings" % self.kind.value,
            status="UNIMPLEMENTED")

    # data-plane ---------------------------------------------------------
    def infer(self, model_name, inputs, outputs=None, **kwargs):
        raise NotImplementedError

    def async_infer(self, callback: Callable, model_name, inputs,
                    outputs=None, **kwargs):
        """callback(result, error)"""
        raise NotImplementedError

    def start_stream(self, callback: Callable):
        raise NotImplementedError

    def stop_stream(self):
        raise NotImplementedError

    def async_stream_infer(self, model_name, inputs, outputs=None, **kwargs):
        raise NotImplementedError

    # shared memory ------------------------------------------------------
    def register_system_shared_memory(self, name, key, byte_size, offset=0):
        raise NotImplementedError

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size):
        raise NotImplementedError

    def unregister_system_shared_memory(self, name=""):
        raise NotImplementedError

    def unregister_tpu_shared_memory(self, name=""):
        raise NotImplementedError

    def close(self):
        pass


class GrpcClientBackend(ClientBackend):
    kind = BackendKind.TRITON_GRPC

    def __init__(self, url: str, verbose: bool = False, retry_policy=None,
                 circuit_breaker=None, endpoint_pool=None):
        import client_tpu.grpc as grpcclient

        self._client = grpcclient.InferenceServerClient(
            url, verbose=verbose, retry_policy=retry_policy,
            circuit_breaker=circuit_breaker, endpoint_pool=endpoint_pool)
        # Pool mode: async_infer rides infer() on a worker pool so the
        # full failover/hedging/retry loop applies (the raw gRPC
        # future API routes to ONE endpoint and cannot fail over —
        # in-flight requests at an endpoint kill would surface as
        # client-visible errors instead of being masked).
        self._executor = None
        if endpoint_pool is not None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(max_workers=32)

    def server_metadata(self):
        return self._client.get_server_metadata(as_json=True)

    def model_metadata(self, model_name, model_version=""):
        return self._client.get_model_metadata(
            model_name, model_version, as_json=True
        )

    def model_config(self, model_name, model_version=""):
        response = self._client.get_model_config(
            model_name, model_version, as_json=True
        )
        return response.get("config", response)

    def model_statistics(self, model_name="", model_version=""):
        return self._client.get_inference_statistics(
            model_name, model_version, as_json=True
        )

    def update_trace_settings(self, model_name="", settings=None):
        return self._client.update_trace_settings(model_name, settings,
                                                  as_json=True)

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        return self._client.infer(model_name, inputs, outputs=outputs,
                                  **kwargs)

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        if self._executor is not None:
            def _work():
                try:
                    callback(self._client.infer(model_name, inputs,
                                                outputs=outputs, **kwargs),
                             None)
                except InferenceServerException as e:
                    callback(None, e)
                except Exception as e:  # noqa: BLE001 — to the callback
                    callback(None, InferenceServerException(str(e)))

            return self._executor.submit(_work)
        return self._client.async_infer(model_name, inputs, callback,
                                        outputs=outputs, **kwargs)

    def start_stream(self, callback):
        self._client.start_stream(callback)

    def stop_stream(self):
        self._client.stop_stream()

    def async_stream_infer(self, model_name, inputs, outputs=None, **kwargs):
        self._client.async_stream_infer(model_name, inputs, outputs=outputs,
                                        **kwargs)

    def register_system_shared_memory(self, name, key, byte_size, offset=0):
        self._client.register_system_shared_memory(name, key, byte_size,
                                                   offset)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size):
        self._client.register_tpu_shared_memory(name, raw_handle, device_id,
                                                byte_size)

    def unregister_system_shared_memory(self, name=""):
        self._client.unregister_system_shared_memory(name)

    def unregister_tpu_shared_memory(self, name=""):
        self._client.unregister_tpu_shared_memory(name)

    def close(self):
        if self._executor is not None:
            self._executor.shutdown(wait=False)
        self._client.close()


class HttpClientBackend(ClientBackend):
    kind = BackendKind.TRITON_HTTP

    def __init__(self, url: str, verbose: bool = False, concurrency: int = 8,
                 retry_policy=None, circuit_breaker=None,
                 endpoint_pool=None):
        import client_tpu.http as httpclient

        self._client = httpclient.InferenceServerClient(
            url, verbose=verbose, concurrency=concurrency,
            retry_policy=retry_policy, circuit_breaker=circuit_breaker,
            endpoint_pool=endpoint_pool,
        )

    def server_metadata(self):
        return self._client.get_server_metadata()

    def model_metadata(self, model_name, model_version=""):
        return self._client.get_model_metadata(model_name, model_version)

    def model_config(self, model_name, model_version=""):
        return self._client.get_model_config(model_name, model_version)

    def model_statistics(self, model_name="", model_version=""):
        return self._client.get_inference_statistics(model_name, model_version)

    def update_trace_settings(self, model_name="", settings=None):
        return self._client.update_trace_settings(model_name, settings)

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        # client_timeout passes through: the HTTP client now has
        # per-call deadline parity with the gRPC client.
        return self._client.infer(model_name, inputs, outputs=outputs,
                                  **kwargs)

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        handle = self._client.async_infer(model_name, inputs, outputs=outputs,
                                          **kwargs)

        # piggyback on the client's worker-pool future — no extra
        # thread per request; the worker stores exceptions rather than
        # raising, so future.result() is safe here
        def _on_done(future):
            result = future.result()
            if isinstance(result, Exception):
                error = (
                    result if isinstance(result, InferenceServerException)
                    else InferenceServerException(str(result))
                )
                callback(None, error)
            else:
                callback(result, None)

        handle._future.add_done_callback(_on_done)
        return handle

    def register_system_shared_memory(self, name, key, byte_size, offset=0):
        self._client.register_system_shared_memory(name, key, byte_size,
                                                   offset)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size):
        self._client.register_tpu_shared_memory(name, raw_handle, device_id,
                                                byte_size)

    def unregister_system_shared_memory(self, name=""):
        self._client.unregister_system_shared_memory(name)

    def unregister_tpu_shared_memory(self, name=""):
        self._client.unregister_tpu_shared_memory(name)

    def close(self):
        self._client.close()


class OpenAiResult:
    """Result shim for OpenAI responses: the worker pairing/final
    plumbing sees the same get_response()/get_parameters() surface as
    Triton results."""

    def __init__(self, body: str, request_id: str, final: bool):
        self.body = body
        self._id = request_id
        self._final = final

    def get_response(self):
        return {"id": self._id}

    def get_parameters(self):
        return {"triton_final_response": self._final}


class OpenAiClientBackend(ClientBackend):
    """Chat-completions client over HTTP with SSE streaming (parity:
    the reference's openai client backend, client_backend/openai/ —
    payload passthrough from the input JSON, one stream callback per
    SSE chunk)."""

    kind = BackendKind.OPENAI

    def __init__(self, url: str, endpoint: str = "/v1/chat/completions",
                 verbose: bool = False):
        if "://" in url:
            url = url.split("://", 1)[1]
        host, _, port = url.partition(":")
        self._host = host
        self._port = int(port or 8000)
        self._endpoint = endpoint if endpoint.startswith("/") \
            else "/" + endpoint
        self._verbose = verbose
        self._stream_callback = None
        self._inflight = threading.Semaphore(0)
        self._inflight_count = 0
        self._lock = threading.Lock()

    # Synthesized schema (parity: ModelParser::InitOpenAI).
    def model_metadata(self, model_name, model_version=""):
        return {
            "name": model_name,
            "platform": "openai",
            "inputs": [{"name": "payload", "datatype": "BYTES",
                        "shape": [1]}],
            "outputs": [],
        }

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "max_batch_size": 0}

    def model_statistics(self, model_name="", model_version=""):
        return {"model_stats": []}

    def server_metadata(self):
        return {"name": "openai-endpoint"}

    @staticmethod
    def _payload_from_inputs(inputs) -> bytes:
        for infer_input in inputs:
            if infer_input.name() == "payload":
                raw = infer_input.raw_data()
                if raw is None:
                    raise InferenceServerException(
                        "payload input has no data")
                # BYTES wire format: strip the 4-byte length prefix.
                return raw[4:] if len(raw) >= 4 else raw
        raise InferenceServerException(
            "OpenAI requests need a 'payload' BYTES input")

    def _post(self, payload: bytes, on_chunk=None) -> str:
        import http.client

        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=120)
        try:
            conn.request("POST", self._endpoint, body=payload,
                         headers={"Content-Type": "application/json"})
            response = conn.getresponse()
            if response.status != 200:
                raise InferenceServerException(
                    "HTTP %d: %s"
                    % (response.status, response.read().decode()[:500])
                )
            if on_chunk is None:
                return response.read().decode()
            buffer = b""
            while True:
                data = response.read1(65536)
                if not data:
                    break
                buffer += data
                while b"\n\n" in buffer:
                    event, buffer = buffer.split(b"\n\n", 1)
                    if not event.startswith(b"data: "):
                        continue
                    chunk = event[6:].decode()
                    if chunk == "[DONE]":
                        continue  # final fires after EOF
                    on_chunk(chunk)
            return ""
        finally:
            conn.close()

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        payload = self._payload_from_inputs(inputs)
        body = self._post(payload)
        return OpenAiResult(body, kwargs.get("request_id", ""), True)

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        payload = self._payload_from_inputs(inputs)
        request_id = kwargs.get("request_id", "")

        def _work():
            try:
                body = self._post(payload)
                callback(OpenAiResult(body, request_id, True), None)
            except InferenceServerException as e:
                callback(None, e)
            except Exception as e:  # transport errors
                callback(None, InferenceServerException(str(e)))

        threading.Thread(target=_work, daemon=True).start()

    def start_stream(self, callback):
        self._stream_callback = callback

    def stop_stream(self):
        self._stream_callback = None

    def async_stream_infer(self, model_name, inputs, outputs=None,
                           **kwargs):
        callback = self._stream_callback
        if callback is None:
            raise InferenceServerException("stream not started")
        payload = self._payload_from_inputs(inputs)
        request_id = kwargs.get("request_id", "")

        def _work():
            try:
                self._post(
                    payload,
                    on_chunk=lambda chunk: callback(
                        OpenAiResult(chunk, request_id, False), None),
                )
                callback(OpenAiResult("", request_id, True), None)
            except InferenceServerException as e:
                callback(OpenAiResult("", request_id, True), e)
            except Exception as e:
                callback(OpenAiResult("", request_id, True),
                         InferenceServerException(str(e)))

        threading.Thread(target=_work, daemon=True).start()


class _RestResult(OpenAiResult):
    """Result shim for plain-HTTP JSON backends (TorchServe,
    TF-Serving REST): the OpenAI shim's worker-facing surface, always
    final, plus JSON decoding."""

    def __init__(self, body: str, request_id: str):
        super().__init__(body, request_id, final=True)

    def as_json(self):
        import json

        return json.loads(self.body) if self.body else {}


class _PlainHttpBackend(ClientBackend):
    """Shared plumbing for non-Triton HTTP inference APIs: one
    http.client connection per request, sync + thread-async."""

    def __init__(self, url: str, verbose: bool = False):
        self._tls = url.startswith("https://")
        if "://" in url:
            url = url.split("://", 1)[1]
        url = url.split("/", 1)[0]  # drop any path component
        host, _, port = url.rpartition(":")
        if host and not port.isdigit():  # IPv6 literal without port
            host, port = url, ""
        self._host = host or url
        self._port = int(port) if port.isdigit() \
            else (443 if self._tls else 8080)
        self._verbose = verbose

    def _request(self, method: str, path: str, body=None,
                 content_type: str = "application/json") -> str:
        import http.client

        conn_cls = (http.client.HTTPSConnection if self._tls
                    else http.client.HTTPConnection)
        conn = conn_cls(self._host, self._port, timeout=120)
        try:
            headers = {"Content-Type": content_type} if body else {}
            conn.request(method, path, body=body, headers=headers)
            response = conn.getresponse()
            data = response.read().decode()
            if response.status != 200:
                raise InferenceServerException(
                    "HTTP %d: %s" % (response.status, data[:500]))
            return data
        finally:
            conn.close()

    def _async(self, callback, work):
        def _run():
            try:
                callback(work(), None)
            except InferenceServerException as e:
                callback(None, e)
            except Exception as e:  # transport errors
                callback(None, InferenceServerException(str(e)))

        threading.Thread(target=_run, daemon=True).start()

    def model_statistics(self, model_name="", model_version=""):
        return {"model_stats": []}

    def start_stream(self, callback):
        raise InferenceServerException(
            "%s does not support streaming" % self.kind.value)

    def async_stream_infer(self, model_name, inputs, outputs=None,
                           **kwargs):
        raise InferenceServerException(
            "%s does not support streaming" % self.kind.value)


class TorchServeBackend(_PlainHttpBackend):
    """TorchServe inference-API client: POST the first input's raw
    bytes to /predictions/<model> (parity: the reference's torchserve
    client backend, client_backend/torchserve/ — file-content POST,
    no output retrieval, no metadata endpoint)."""

    kind = BackendKind.TORCHSERVE

    # TorchServe has no v2 metadata endpoint; synthesize the reference
    # shape (one BYTES "data" input fed from files or generated data).
    def model_metadata(self, model_name, model_version=""):
        return {
            "name": model_name,
            "platform": "torchserve",
            "inputs": [{"name": "data", "datatype": "BYTES",
                        "shape": [1]}],
            "outputs": [],
        }

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "max_batch_size": 0}

    def server_metadata(self):
        return {"name": "torchserve-endpoint"}

    @staticmethod
    def _body_from_inputs(inputs) -> bytes:
        for infer_input in inputs:
            raw = infer_input.raw_data()
            if raw is None:
                continue
            if infer_input.datatype() == "BYTES":
                # Concatenate every length-prefixed element's payload.
                parts, offset = [], 0
                while offset + 4 <= len(raw):
                    (length,) = np.frombuffer(
                        raw, np.uint32, count=1, offset=offset)
                    offset += 4
                    parts.append(raw[offset:offset + length])
                    offset += int(length)
                return b"".join(parts) if parts else raw
            return raw
        raise InferenceServerException(
            "TorchServe requests need one input with data")

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        body = self._body_from_inputs(inputs)
        data = self._request(
            "POST", "/predictions/%s" % model_name, body,
            content_type="application/octet-stream")
        return _RestResult(data, kwargs.get("request_id", ""))

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        self._async(callback,
                    lambda: self.infer(model_name, inputs, outputs,
                                       **kwargs))


class TfServingBackend(_PlainHttpBackend):
    """TensorFlow-Serving client over the REST predict API
    (/v1/models/<m>:predict, columnar "inputs" format). The reference
    uses the gRPC PredictionService (client_backend/tensorflow_serving/
    tfserve_grpc_client.cc Predict) — same request semantics; REST is
    used here so no TensorFlow proto tree is vendored."""

    kind = BackendKind.TFSERVING

    def model_metadata(self, model_name, model_version=""):
        import json

        path = "/v1/models/%s" % model_name
        if model_version:
            path += "/versions/%s" % model_version
        try:
            meta = json.loads(self._request("GET", path + "/metadata"))
        except Exception:
            meta = {}
        inputs, outputs = [], []
        sig = (meta.get("metadata", {}).get("signature_def", {})
               .get("signature_def", {}).get("serving_default", {}))
        for name, spec in (sig.get("inputs") or {}).items():
            dims = [int(d.get("size", -1))
                    for d in spec.get("tensor_shape", {}).get("dim", [])]
            inputs.append({"name": name,
                           "datatype": _TF_TO_TRITON_DTYPE.get(
                               spec.get("dtype", ""), "FP32"),
                           "shape": dims or [-1]})
        for name, spec in (sig.get("outputs") or {}).items():
            outputs.append({"name": name, "datatype": "FP32",
                            "shape": [-1]})
        return {"name": model_name, "platform": "tensorflow_serving",
                "inputs": inputs, "outputs": outputs}

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "max_batch_size": 0}

    def server_metadata(self):
        return {"name": "tfserving-endpoint"}

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        import json

        payload = {"inputs": {}}
        for infer_input in inputs:
            array = infer_input.numpy_data()
            if array is None:
                raise InferenceServerException(
                    "TF-Serving REST needs numpy-backed inputs")
            if array.dtype == np.object_:
                payload["inputs"][infer_input.name()] = [
                    v.decode() if isinstance(v, bytes) else str(v)
                    for v in array.ravel()
                ]
            else:
                payload["inputs"][infer_input.name()] = array.tolist()
        version = kwargs.get("model_version", "")
        path = "/v1/models/%s" % model_name
        if version:
            path += "/versions/%s" % version
        data = self._request("POST", path + ":predict",
                             json.dumps(payload).encode())
        return _RestResult(data, kwargs.get("request_id", ""))

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        self._async(callback,
                    lambda: self.infer(model_name, inputs, outputs,
                                       **kwargs))


_TF_TO_TRITON_DTYPE = {
    "DT_HALF": "FP16", "DT_BFLOAT16": "BF16", "DT_FLOAT": "FP32",
    "DT_DOUBLE": "FP64", "DT_INT8": "INT8", "DT_INT16": "INT16",
    "DT_INT32": "INT32", "DT_INT64": "INT64", "DT_UINT8": "UINT8",
    "DT_UINT16": "UINT16", "DT_UINT32": "UINT32", "DT_UINT64": "UINT64",
    "DT_STRING": "BYTES", "DT_BOOL": "BOOL",
}

# triton wire dtype -> tensorflow.DataType enum value (types.proto).
TRITON_TO_TF_DTYPE = {
    "FP16": 19, "BF16": 14, "FP32": 1, "FP64": 2, "INT8": 6, "INT16": 5,
    "INT32": 3, "INT64": 9, "UINT8": 4, "UINT16": 17, "UINT32": 22,
    "UINT64": 23, "BYTES": 7, "BOOL": 10,
}
_TF_ENUM_TO_NP = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8, 5: np.int16,
    6: np.int8, 9: np.int64, 10: np.bool_, 17: np.uint16, 19: np.float16,
    22: np.uint32, 23: np.uint64,
}


class _TfsResult:
    """PredictResponse wrapper with the InferResult reading surface."""

    def __init__(self, response, request_id=""):
        self._response = response
        self._id = request_id

    def as_numpy(self, name):
        tensor = self._response.outputs.get(name)
        if tensor is None:
            return None
        shape = [d.size for d in tensor.tensor_shape.dim]
        if tensor.dtype == 7:  # DT_STRING
            return np.array(list(tensor.string_val),
                            dtype=np.object_).reshape(shape)
        np_dtype = _TF_ENUM_TO_NP.get(tensor.dtype)
        if np_dtype is None:
            raise InferenceServerException(
                "unsupported TF dtype %d" % tensor.dtype)
        if tensor.tensor_content:
            return np.frombuffer(
                tensor.tensor_content, dtype=np_dtype).reshape(shape)
        if len(tensor.half_val):  # raw 16-bit patterns widened to int32
            return np.array(list(tensor.half_val),
                            dtype=np.uint16).view(np_dtype).reshape(shape)
        for field in ("float_val", "double_val", "int_val", "int64_val",
                      "bool_val", "uint32_val", "uint64_val"):
            values = getattr(tensor, field)
            if len(values):
                return np.array(list(values), dtype=np_dtype).reshape(shape)
        return np.zeros(shape, dtype=np_dtype)

    def get_response(self):
        return self._response

    def request_id(self):
        return self._id

    def is_final_response(self):
        return True


class TfServingGrpcBackend(ClientBackend):
    """TensorFlow-Serving over the gRPC PredictionService — the
    reference's native protocol (client_backend/tensorflow_serving/
    tfserve_grpc_client.cc Predict), speaking the compiled
    wire-compatible proto subset in client_tpu.protocol."""

    kind = BackendKind.TFSERVING

    def __init__(self, url: str, verbose: bool = False):
        import grpc
        from concurrent.futures import ThreadPoolExecutor

        from client_tpu.protocol import tensorflow_serving_apis_pb2 as tfs

        self._tfs = tfs
        self._url = url
        self._verbose = verbose
        self._channel = grpc.insecure_channel(url)
        self._predict = self._channel.unary_unary(
            "/tensorflow.serving.PredictionService/Predict",
            request_serializer=tfs.PredictRequest.SerializeToString,
            response_deserializer=tfs.PredictResponse.FromString,
        )
        self._executor = ThreadPoolExecutor(max_workers=8)

    def close(self):
        self._executor.shutdown(wait=False)
        self._channel.close()

    # TF-Serving exposes no KServe metadata; shapes come from the
    # harness's --shape overrides (reference behavior for this kind).
    def server_metadata(self):
        return {"name": "tfserving-endpoint", "protocol": "grpc"}

    def model_metadata(self, model_name, model_version=""):
        return {"name": model_name, "platform": "tensorflow_serving",
                "inputs": [], "outputs": []}

    def model_config(self, model_name, model_version=""):
        return {"name": model_name, "max_batch_size": 0}

    def model_statistics(self, model_name="", model_version=""):
        return {"model_stats": []}

    def _build_request(self, model_name, inputs, model_version=""):
        request = self._tfs.PredictRequest()
        request.model_spec.name = model_name
        if model_version:
            request.model_spec.version.value = int(model_version)
        for infer_input in inputs:
            array = infer_input.numpy_data()
            if array is None:
                raise InferenceServerException(
                    "TF-Serving needs numpy-backed inputs")
            tensor = request.inputs[infer_input.name()]
            tensor.dtype = TRITON_TO_TF_DTYPE.get(
                infer_input.datatype(), 1)
            for dim in array.shape:
                tensor.tensor_shape.dim.add().size = int(dim)
            if array.dtype == np.object_:
                tensor.string_val.extend(
                    v if isinstance(v, bytes) else str(v).encode()
                    for v in array.ravel()
                )
            else:
                tensor.tensor_content = np.ascontiguousarray(
                    array).tobytes()
        return request

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        import grpc

        request = self._build_request(
            model_name, inputs, kwargs.get("model_version", ""))
        timeout = kwargs.get("client_timeout")
        try:
            response = self._predict(request, timeout=timeout)
        except grpc.RpcError as e:
            raise InferenceServerException(
                "tfserving predict failed: %s" % e, status="UNAVAILABLE")
        return _TfsResult(response, kwargs.get("request_id", ""))

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        def run():
            try:
                callback(self.infer(model_name, inputs, outputs, **kwargs),
                         None)
            except Exception as e:  # noqa: BLE001 — delivered to callback
                callback(None, e)

        self._executor.submit(run)

    def start_stream(self, callback):
        raise InferenceServerException(
            "tfserving does not support streaming", status="UNIMPLEMENTED")

    def stop_stream(self):
        raise InferenceServerException(
            "tfserving does not support streaming", status="UNIMPLEMENTED")

    def async_stream_infer(self, model_name, inputs, outputs=None,
                           **kwargs):
        raise InferenceServerException(
            "tfserving does not support streaming", status="UNIMPLEMENTED")

    def register_system_shared_memory(self, *args, **kwargs):
        raise InferenceServerException(
            "tfserving does not support shared memory",
            status="UNIMPLEMENTED")

    def register_tpu_shared_memory(self, *args, **kwargs):
        raise InferenceServerException(
            "tfserving does not support shared memory",
            status="UNIMPLEMENTED")

    def unregister_system_shared_memory(self, name=""):
        pass

    def unregister_tpu_shared_memory(self, name=""):
        pass


class InProcessBackend(ClientBackend):
    """Runs against an InferenceServerCore in this process — no RPC,
    no serialization of tensor contents beyond proto assembly. The
    TPU analogue of the reference's triton_c_api backend (in-process
    server via dlopen, triton_c_api/triton_loader.cc:526)."""

    kind = BackendKind.IN_PROCESS

    def __init__(self, core, max_workers: int = 8, retry_policy=None,
                 circuit_breaker=None):
        from concurrent.futures import ThreadPoolExecutor

        from google.protobuf import json_format

        self._core = core
        self._json = json_format
        self._executor = ThreadPoolExecutor(max_workers=max_workers)
        self._stream_callback = None
        # Retry/breaker parity with the RPC backends so chaos runs can
        # measure recovery with zero serialization in the loop.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker

    def server_metadata(self):
        return self._json.MessageToDict(self._core.server_metadata(),
                                        preserving_proto_field_name=True)

    def model_metadata(self, model_name, model_version=""):
        return self._json.MessageToDict(
            self._core.model_metadata(model_name, model_version),
            preserving_proto_field_name=True,
        )

    def model_config(self, model_name, model_version=""):
        return self._json.MessageToDict(
            self._core.model_config(model_name, model_version).config,
            preserving_proto_field_name=True,
        )

    def model_statistics(self, model_name="", model_version=""):
        return self._json.MessageToDict(
            self._core.model_statistics(model_name, model_version),
            preserving_proto_field_name=True,
        )

    def update_trace_settings(self, model_name="", settings=None):
        updates = {}
        for key, value in (settings or {}).items():
            if value is None:
                updates[key] = []
            elif isinstance(value, (list, tuple)):
                updates[key] = [str(v) for v in value]
            else:
                updates[key] = [str(value)]
        return self._core.trace_setting(model_name, updates)

    def _build_request(self, model_name, inputs, outputs, **kwargs):
        from client_tpu.grpc._utils import get_inference_request

        kwargs.pop("client_timeout", None)
        return get_inference_request(
            model_name=model_name, inputs=inputs, outputs=outputs, **kwargs
        )

    def _infer_with_retry(self, request):
        from client_tpu.grpc._utils import InferResult
        from client_tpu.robust import call_with_retry

        return call_with_retry(
            lambda _remaining: InferResult(self._core.infer(request)),
            self._retry_policy, self._breaker,
        )

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        request = self._build_request(model_name, inputs, outputs, **kwargs)
        return self._infer_with_retry(request)

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        request = self._build_request(model_name, inputs, outputs, **kwargs)

        def _work():
            try:
                callback(self._infer_with_retry(request), None)
            except InferenceServerException as e:
                callback(None, e)
            except Exception as e:  # any failure must release the slot
                callback(None, InferenceServerException(str(e)))

        return self._executor.submit(_work)

    def start_stream(self, callback):
        self._stream_callback = callback

    def stop_stream(self):
        self._stream_callback = None

    def async_stream_infer(self, model_name, inputs, outputs=None, **kwargs):
        from client_tpu.grpc._utils import InferResult

        if self._stream_callback is None:
            raise InferenceServerException("stream is not running")
        callback = self._stream_callback
        request = self._build_request(model_name, inputs, outputs, **kwargs)

        def _work():
            try:
                for stream_response in self._core.stream_infer(request):
                    if stream_response.error_message:
                        callback(None, InferenceServerException(
                            stream_response.error_message))
                    else:
                        callback(InferResult(stream_response.infer_response),
                                 None)
            except InferenceServerException as e:
                callback(None, e)

        return self._executor.submit(_work)

    def register_system_shared_memory(self, name, key, byte_size, offset=0):
        self._core.register_system_shm(name, key, offset, byte_size)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size):
        self._core.register_tpu_shm(name, raw_handle, device_id, byte_size)

    def unregister_system_shared_memory(self, name=""):
        self._core.unregister_system_shm(name)

    def unregister_tpu_shared_memory(self, name=""):
        self._core.unregister_tpu_shm(name)

    def close(self):
        self._executor.shutdown(wait=False)


class MockBackend(ClientBackend):
    """Fakes a server with a programmable per-request delay and
    optional failures — the fixture that lets every load manager and
    profiler test run serverless (parity: mock_client_backend.h:471,
    which spawns detached threads that sleep then fire the async
    callback)."""

    kind = BackendKind.MOCK

    class Stats:
        def __init__(self):
            self.lock = threading.Lock()
            self.infer_calls = 0
            self.async_infer_calls = 0
            self.stream_calls = 0
            self.sequence_ids: List[int] = []
            self.request_parameters: List[dict] = []

    def __init__(
        self,
        delay_s: float = 0.0,
        stats: Optional["MockBackend.Stats"] = None,
        fail_every: int = 0,
        model_metadata_dict: Optional[dict] = None,
        model_config_dict: Optional[dict] = None,
        model_configs: Optional[dict] = None,
    ):
        self._delay = delay_s
        self.stats = stats if stats is not None else MockBackend.Stats()
        self._fail_every = fail_every
        self._count = 0
        self._stream_callback = None
        self._metadata = model_metadata_dict or {
            "name": "mock", "versions": ["1"], "platform": "mock",
            "inputs": [
                {"name": "INPUT0", "datatype": "FP32", "shape": [16]},
            ],
            "outputs": [
                {"name": "OUTPUT0", "datatype": "FP32", "shape": [16]},
            ],
        }
        self._config = model_config_dict or {
            "name": "mock", "max_batch_size": 0,
        }
        # Per-model-name config overrides (composing-model tests).
        self._configs = model_configs or {}

    def _maybe_fail(self):
        self._count += 1
        if self._fail_every and self._count % self._fail_every == 0:
            raise InferenceServerException("mock failure", status="INTERNAL")

    def _record(self, kind: str, kwargs):
        with self.stats.lock:
            if kind == "infer":
                self.stats.infer_calls += 1
            elif kind == "async":
                self.stats.async_infer_calls += 1
            else:
                self.stats.stream_calls += 1
            if kwargs.get("sequence_id"):
                self.stats.sequence_ids.append(kwargs["sequence_id"])
            self.stats.request_parameters.append(dict(kwargs))

    def server_metadata(self):
        return {"name": "mock_server", "version": "0", "extensions": []}

    def model_metadata(self, model_name, model_version=""):
        return dict(self._metadata, name=model_name)

    def model_config(self, model_name, model_version=""):
        if model_name in self._configs:
            return dict(self._configs[model_name], name=model_name)
        return dict(self._config, name=model_name)

    def model_statistics(self, model_name="", model_version=""):
        return {"model_stats": [{
            "name": model_name or "mock", "version": "1",
            "inference_count": self.stats.infer_calls
            + self.stats.async_infer_calls,
            "execution_count": self.stats.infer_calls
            + self.stats.async_infer_calls,
            "inference_stats": {
                "success": {"count": self._count, "ns": 0},
                "fail": {"count": 0, "ns": 0},
                "queue": {"count": self._count, "ns": 1000},
                "compute_input": {"count": self._count, "ns": 1000},
                "compute_infer": {"count": self._count, "ns": 1000},
                "compute_output": {"count": self._count, "ns": 1000},
            },
        }]}

    def _result(self):
        class _R:
            def as_numpy(self, name):
                return np.zeros(16, dtype=np.float32)

            def get_response(self):
                return {}

            def get_parameters(self):
                return {"triton_final_response": True}

        return _R()

    def infer(self, model_name, inputs, outputs=None, **kwargs):
        self._record("infer", kwargs)
        self._maybe_fail()
        if self._delay:
            import time

            time.sleep(self._delay)
        return self._result()

    def async_infer(self, callback, model_name, inputs, outputs=None,
                    **kwargs):
        self._record("async", kwargs)

        def _work():
            import time

            try:
                self._maybe_fail()
            except InferenceServerException as e:
                callback(None, e)
                return
            if self._delay:
                time.sleep(self._delay)
            callback(self._result(), None)

        thread = threading.Thread(target=_work, daemon=True)
        thread.start()
        return thread

    def start_stream(self, callback):
        self._stream_callback = callback

    def stop_stream(self):
        self._stream_callback = None

    def async_stream_infer(self, model_name, inputs, outputs=None, **kwargs):
        self._record("stream", kwargs)
        callback = self._stream_callback

        def _work():
            import time

            if self._delay:
                time.sleep(self._delay)
            callback(self._result(), None)

        thread = threading.Thread(target=_work, daemon=True)
        thread.start()
        return thread

    def register_system_shared_memory(self, name, key, byte_size, offset=0):
        pass

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size):
        pass

    def unregister_system_shared_memory(self, name=""):
        pass

    def unregister_tpu_shared_memory(self, name=""):
        pass


class ClientBackendFactory:
    """Creates per-worker backends (parity: client_backend.h:268)."""

    def __init__(self, kind: BackendKind, url: str = "", core=None,
                 verbose: bool = False, http_concurrency: int = 8,
                 mock_delay_s: float = 0.0, mock_stats=None,
                 openai_endpoint: str = "/v1/chat/completions",
                 tfserving_grpc: bool = True, retry_policy=None,
                 breaker_factory=None, endpoint_pool=None):
        self.kind = kind
        self._url = url
        self._core = core
        self._verbose = verbose
        self._http_concurrency = http_concurrency
        self._mock_delay = mock_delay_s
        self._mock_stats = mock_stats
        self._openai_endpoint = openai_endpoint
        # gRPC PredictionService is TF-Serving's native protocol
        # (reference parity); False selects the REST predict API.
        self._tfserving_grpc = tfserving_grpc
        # Robustness wiring: the policy is immutable and shared; each
        # backend (= each worker's client) gets its OWN breaker so one
        # worker tripping open doesn't blind the others' measurements.
        self._retry_policy = retry_policy
        self._breaker_factory = breaker_factory
        # Multi-endpoint runs share ONE EndpointPool across every
        # worker's client: fleet health (breakers, EWMA, ejections) is
        # a property of the fleet, not of one worker, and the pooled
        # counters then cover the whole run for the failover report.
        self.endpoint_pool = endpoint_pool

    def _breaker(self):
        return self._breaker_factory() if self._breaker_factory else None

    def create(self, raw: bool = False) -> ClientBackend:
        # raw=True drops the retry policy / circuit breaker: fault- and
        # load-injection callers (e.g. the --overload burst) must hit
        # the server with every submission — a retrying burst paces
        # itself on Retry-After and never sustains saturation.
        retry_policy = None if raw else self._retry_policy
        breaker = None if raw else self._breaker()
        if self.kind == BackendKind.TRITON_GRPC:
            return GrpcClientBackend(self._url, self._verbose,
                                     retry_policy=retry_policy,
                                     circuit_breaker=breaker,
                                     endpoint_pool=self.endpoint_pool)
        if self.kind == BackendKind.TRITON_HTTP:
            return HttpClientBackend(self._url, self._verbose,
                                     self._http_concurrency,
                                     retry_policy=retry_policy,
                                     circuit_breaker=breaker,
                                     endpoint_pool=self.endpoint_pool)
        if self.kind == BackendKind.OPENAI:
            return OpenAiClientBackend(self._url, self._openai_endpoint,
                                       self._verbose)
        if self.kind == BackendKind.TORCHSERVE:
            return TorchServeBackend(self._url, self._verbose)
        if self.kind == BackendKind.TFSERVING:
            if self._tfserving_grpc:
                return TfServingGrpcBackend(self._url, self._verbose)
            return TfServingBackend(self._url, self._verbose)
        if self.kind == BackendKind.IN_PROCESS:
            if self._core is None:
                raise InferenceServerException(
                    "in-process backend requires a server core"
                )
            return InProcessBackend(self._core,
                                    retry_policy=retry_policy,
                                    circuit_breaker=breaker)
        if self.kind == BackendKind.MOCK:
            return MockBackend(self._mock_delay, self._mock_stats)
        raise InferenceServerException("unknown backend kind %s" % self.kind)
