"""Result reporting: stdout summary, CSV rows (parity: report_writer.h)
and the JSON profile export consumed by the genai layer (parity:
profile_data_exporter.h:54-94)."""

from __future__ import annotations

import csv
import json
from typing import List, Optional

from client_tpu.perf.profiler import PerfStatus


def print_report(results: List[PerfStatus], percentile: int = 0,
                 mode: str = "concurrency") -> None:
    for status in results:
        level = (
            "Concurrency: %d" % status.concurrency
            if mode == "concurrency"
            else "Request rate: %.1f" % status.request_rate
        )
        print("%s, throughput: %.2f infer/sec, avg latency %.0f usec"
              % (level, status.throughput, status.avg_latency_us))
        pcts = ", ".join(
            "p%d %.0f" % (p, v)
            for p, v in sorted(status.latency_percentiles.items())
        )
        print("    latency percentiles (usec): %s" % pcts)
        if status.delayed_count:
            print("    delayed requests: %d" % status.delayed_count)
        if status.error_count:
            print("    errors: %d" % status.error_count)
        for entry in status.server_stats.get("model_stats", []):
            stats = entry.get("inference_stats", {})
            count = entry.get("inference_count", 0)
            if not count:
                continue

            def us(section):
                return stats.get(section, {}).get("ns", 0) / count / 1000.0

            print(
                "    server %s (this window): %d inferences, "
                "%d executions, queue %.0f us, compute in/infer/out "
                "%.0f/%.0f/%.0f us"
                % (entry.get("name", "?"), count,
                   entry.get("execution_count", 0), us("queue"),
                   us("compute_input"), us("compute_infer"),
                   us("compute_output")))
            hits = int(entry.get("cache_hit_count", 0))
            misses = int(entry.get("cache_miss_count", 0))
            if hits or misses:
                # Window-delta cache summary. The mean path latencies
                # come from the cache_hit/cache_miss duration sections
                # (end-to-end per path); queue/compute sections above
                # EXCLUDE hits — the caveat printed at startup.
                ratio = hits / (hits + misses) * 100.0

                def path_us(section, n):
                    return (stats.get(section, {}).get("ns", 0) / n
                            / 1000.0 if n else 0.0)

                parts = ["%.1f%% hit ratio (%d hits / %d misses)"
                         % (ratio, hits, misses)]
                if hits:
                    parts.append("hit mean %.0f us"
                                 % path_us("cache_hit", hits))
                if misses:
                    parts.append("miss mean %.0f us"
                                 % path_us("cache_miss", misses))
                print("    cache %s (this window): %s"
                      % (entry.get("name", "?"), ", ".join(parts)))
            stream = entry.get("stream_stats") or {}
            if stream.get("response_count"):
                # Server-observed streaming-token telemetry (means
                # from ModelStatistics; the /metrics histograms below
                # add the distributions when a metrics URL is
                # scraped).
                first = stream.get("first_response") or {}
                inter = stream.get("inter_response") or {}
                parts = ["%d responses over %d streams"
                         % (int(stream.get("response_count", 0)),
                            int(stream.get("stream_count", 0)))]
                if first.get("count"):
                    parts.append("TTFT mean %.0f us"
                                 % (first.get("ns", 0)
                                    / first["count"] / 1000.0))
                if inter.get("count"):
                    parts.append("ITL mean %.0f us"
                                 % (inter.get("ns", 0)
                                    / inter["count"] / 1000.0))
                print("    stream %s (this window): %s"
                      % (entry.get("name", "?"), ", ".join(parts)))
            seq = entry.get("sequence_stats") or {}
            if seq.get("step_count") or seq.get("active_sequences"):
                slot_total = seq.get("slot_total", 0)
                active = seq.get("active_sequences", 0)
                util = active / slot_total if slot_total else 0.0
                executions = entry.get("execution_count", 0)
                fused_batch = count / executions if executions else 0.0
                print(
                    "    sequences %s: %d active / %d slots "
                    "(%.0f%% utilized), %d started, %d completed, "
                    "%d steps (%d via dynamic batcher, mean fused "
                    "batch %.2f), backlog %d, idle-reclaimed %d"
                    % (entry.get("name", "?"), active, slot_total,
                       util * 100.0, seq.get("sequences_started", 0),
                       seq.get("sequences_completed", 0),
                       seq.get("step_count", 0),
                       seq.get("fused_steps", 0), fused_batch,
                       seq.get("backlog_depth", 0),
                       seq.get("idle_reclaimed_total", 0)))
        if status.tpu_metrics:
            _print_histogram_lines(status)
            hbm = status.tpu_metrics.get("hbm_used_bytes")
            util = status.tpu_metrics.get("hbm_utilization")
            parts = []
            if hbm:
                parts.append("HBM used avg %.1f MiB / max %.1f MiB"
                             % (hbm["avg"] / 2**20, hbm["max"] / 2**20))
            if util:
                parts.append("HBM util avg %.1f%%" % (util["avg"] * 100))
            if parts:
                print("    server TPU: %s" % ", ".join(parts))
            # Device-axis line (server/devstats.py families): duty
            # cycle over the window, per-model-attributed HBM peak
            # (the ledger total's max), and XLA compiles in window.
            duty = status.tpu_metrics.get("device_duty_cycle")
            ledger = status.tpu_metrics.get("hbm_model_bytes")
            compiles = status.tpu_metrics.get("compile_total")
            parts = []
            if duty:
                parts.append("duty cycle avg %.1f%% / max %.1f%%"
                             % (duty["avg"] * 100, duty["max"] * 100))
            if ledger:
                parts.append("model HBM peak %.1f MiB"
                             % (ledger["max"] / 2**20))
            if compiles and compiles.get("delta"):
                parts.append("%d XLA compiles in window"
                             % int(compiles["delta"]))
            if parts:
                print("    server device: %s" % ", ".join(parts))
            healthy = status.tpu_metrics.get("replica_healthy")
            total = status.tpu_metrics.get("replica_count")
            if healthy and total and total.get("max"):
                parts = ["healthy avg %.1f / %.0f"
                         % (healthy["avg"], total["max"])]
                for fam, label in (("replica_ejected_total", "ejections"),
                                   ("replica_readmitted_total",
                                    "readmissions"),
                                   ("replica_redispatch_total",
                                    "re-dispatches")):
                    window = status.tpu_metrics.get(fam)
                    if window and window.get("delta"):
                        parts.append("%s %d" % (label,
                                                int(window["delta"])))
                print("    server replicas: %s" % ", ".join(parts))
            _print_scaling_line(status)
        if not status.on_target:
            print("    WARNING: measurement did not stabilize")


def _print_scaling_line(status: PerfStatus) -> None:
    """The autoscale timeline: replica-seconds consumed, fleet-size
    movement across the window (gauge-aware delta/min), scale events
    by direction, and shed decisions with their reasons — rendered
    only when the controller's families were scraped."""
    seconds = status.tpu_metrics.get("replica_seconds_total")
    events = status.tpu_metrics.get("scale_events_total")
    if not seconds and not events:
        return
    parts = []
    if seconds and seconds.get("delta"):
        parts.append("replica-seconds %.1f" % seconds["delta"])
    desired = status.tpu_metrics.get("replica_desired")
    if desired and desired.get("max"):
        parts.append("desired peak %.0f / trough %.0f"
                     % (desired["max"],
                        desired.get("min", desired["max"])))
    if events and events.get("delta"):
        parts.append("%d scale events in window" % int(events["delta"]))
    sheds = status.tpu_metrics.get("shed_total")
    if sheds and sheds.get("delta"):
        parts.append("sheds %d" % int(sheds["delta"]))
    if parts:
        print("    server scaling: %s" % ", ".join(parts))


def _print_histogram_lines(status: PerfStatus) -> None:
    """Server-side latency quantiles estimated from the scraped
    /metrics histogram window deltas, printed beside the
    client-observed percentiles — the queueing-vs-network
    decomposition a client-only harness cannot do. TTFT/ITL lines
    appear when the model streamed this window."""
    from client_tpu.perf.metrics_manager import histogram_quantiles

    quantiles = histogram_quantiles(status.tpu_metrics)
    for key in sorted(k for k in quantiles
                      if k.startswith("request_duration_us|")):
        model_name = key.split("|", 1)[1]
        q = quantiles[key]
        line = ("    server %s /metrics histogram (this window): "
                "request p50 %.0f us / p99 %.0f us over %d requests"
                % (model_name, q["p50_us"], q["p99_us"], q["count"]))
        client_p50 = status.latency_percentiles.get(50)
        client_p99 = status.latency_percentiles.get(99)
        if client_p50 is not None and client_p99 is not None:
            line += (" (client p50 %.0f / p99 %.0f)"
                     % (client_p50, client_p99))
        print(line)
    for key in sorted(k for k in quantiles
                      if k.startswith("stream_first_response_us|")):
        model_name = key.split("|", 1)[1]
        first = quantiles[key]
        line = ("    server %s stream histograms (this window): TTFT "
                "p50 %.0f us / p99 %.0f us" % (model_name,
                                               first["p50_us"],
                                               first["p99_us"]))
        inter = quantiles.get("stream_inter_response_us|%s" % model_name)
        if inter:
            line += (", ITL p50 %.0f us / p99 %.0f us (%d gaps)"
                     % (inter["p50_us"], inter["p99_us"],
                        inter["count"]))
        print(line)


# Span name -> report stage for the --trace stage-attribution table.
# Spans outside this map land in "other"; the "request" root span is
# the denominator (end-to-end server time), never a stage.
STAGE_SPANS = {
    "decode": "decode",
    "cache_lookup": "cache",
    "cache_wait": "cache",
    "cache_insert": "cache",
    "queue": "queue",
    "sequence_slot_wait": "queue",
    "batch_execute": "execute",
    "device_execute": "execute",
    "stream_response": "execute",
    # Per-stage ensemble spans overlap the member queue/batch_execute
    # spans they parent — attribution view, not a work count (same
    # rule as shared batch spans).
    "ensemble_step": "execute",
    "relay_fetch": "fetch",
    "encode": "encode",
}
STAGE_ORDER = ("decode", "cache", "queue", "execute", "fetch", "encode",
               "other")


def harvest_trace(path: str) -> List[dict]:
    """Parses a compact-mode trace file into per-request stage
    attribution: one {root_ns, stages: {stage: ns}, model} entry per
    sampled request. Unparseable lines are skipped — a trace file is
    diagnostic evidence, never a reason to fail the report."""
    import json

    from client_tpu.server.tracing import stage_durations

    requests = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return requests
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            record = json.loads(line)
        except ValueError:
            continue
        spans = record.get("spans") or []
        root = next(
            (s for s in spans if s.get("name") == "request"), None)
        if root is None:
            continue
        root_ns = max(
            int(root.get("end_ns", 0)) - int(root.get("start_ns", 0)), 0)
        requests.append({
            "root_ns": root_ns,
            "stages": stage_durations(spans, STAGE_SPANS),
            "model": record.get("model_name", ""),
        })
    return requests


def print_trace_report(path: str) -> None:
    """The --trace stage-attribution table: per-stage p50/p99 across
    sampled requests plus each stage's share of p50 end-to-end server
    time — the measured replacement for relay_fetch_ms_est. The
    coverage line is the CI trace smoke's gate."""
    import numpy as np

    requests = harvest_trace(path)
    if not requests:
        print("Trace summary: no sampled requests in %s" % path)
        return
    roots = np.array([r["root_ns"] for r in requests], dtype=float)
    root_p50 = float(np.percentile(roots, 50))
    root_sum = float(roots.sum())
    print("Trace summary (%d sampled requests, %s):"
          % (len(requests), path))
    print("    %-8s %12s %12s %8s" % ("stage", "p50 us", "p99 us",
                                      "share"))
    tracked_sum = 0.0
    qef_sum = 0.0
    for stage in STAGE_ORDER:
        values = np.array([r["stages"].get(stage, 0) for r in requests],
                          dtype=float)
        if not values.any():
            continue
        p50 = float(np.percentile(values, 50))
        p99 = float(np.percentile(values, 99))
        # Shares are sum-based (this stage's total time across sampled
        # requests over total server time): per-stage p50s are not
        # additive when variance is high (a compile spike lands in one
        # request's execute AND its root; percentile sums would
        # under-attribute it).
        share = values.sum() / root_sum * 100.0 if root_sum else 0.0
        tracked_sum += values.sum()
        if stage in ("queue", "execute", "fetch"):
            qef_sum += values.sum()
        print("    %-8s %12.1f %12.1f %7.1f%%"
              % (stage, p50 / 1000.0, p99 / 1000.0, share))
    coverage = tracked_sum / root_sum * 100.0 if root_sum else 0.0
    qef = qef_sum / root_sum * 100.0 if root_sum else 0.0
    print("    server p50 %.1f us; stage coverage %.1f%% of server "
          "span time (queue+execute+fetch %.1f%%)"
          % (root_p50 / 1000.0, coverage, qef))


def print_slo_report(metrics, strict: bool = False) -> bool:
    """The --slo summary + compliance verdict, from one scraped
    ``TpuMetrics`` (tpu_slo_* families): per model, the declared
    targets, fast/slow burn rates, budget remaining, and the
    multi-window healthy verdict — printed next to the histogram
    quantiles the same scrape carries. Returns True when every model
    is compliant: ``tpu_slo_healthy`` is 1 everywhere and (``strict``)
    no fast window burns above 1 — the CI-friendly exit code the
    --slo flag maps to."""
    models = sorted(metrics.slo_healthy)
    if not models:
        # The operator explicitly asked for enforcement: a scrape with
        # no tpu_slo_* series (slo block lost in a config refactor,
        # wrong --metrics-url) must FAIL, not pass vacuously.
        print("SLO summary: no tpu_slo_* series in the scrape — no "
              "model declares an `slo` block (or the metrics source "
              "is wrong); treating as a violation")
        return False
    compliant = True
    print("SLO summary (from the final /metrics scrape):")
    for model_name in models:
        targets = []
        for objective in ("p99_latency_us", "ttft_p99_us",
                          "availability"):
            value = metrics.slo_target.get(
                "%s|o%s" % (model_name, objective))
            if value is not None:
                targets.append(
                    "%s=%g" % (objective, value))
        fast = metrics.slo_burn_rate.get("%s|wfast" % model_name, 0.0)
        slow = metrics.slo_burn_rate.get("%s|wslow" % model_name, 0.0)
        budget = metrics.slo_budget_remaining.get(model_name, 1.0)
        healthy = metrics.slo_healthy.get(model_name, 1.0) >= 1.0
        print("    %s: %s; burn fast %.2fx / slow %.2fx, budget "
              "remaining %.0f%%, verdict %s"
              % (model_name, ", ".join(targets) or "no targets",
                 fast, slow, budget * 100.0,
                 "HEALTHY" if healthy else "UNHEALTHY"))
        if not healthy or (strict and fast > 1.0):
            compliant = False
    print("    SLO compliance: %s"
          % ("PASS" if compliant else "FAIL"))
    return compliant


def print_qos_report(results: List[PerfStatus],
                     description: str = "") -> None:
    """The --priority-mix/--tenant summary: per-priority-class
    client-side throughput, p50/p99 and errors (from the labeled
    request records), paired with the server's window-delta QoS
    counters (rejects, queue-deadline timeouts, sheds, mean queue time
    per class) and the per-tenant admission accounting — same
    window-delta discipline as the cache and failover summaries."""
    import numpy as np

    print("QoS summary (%s):" % (description or "priority classes"))
    by_class: dict = {}
    window_s = 0.0
    for status in results:
        window_s += (status.window_end_ns - status.window_start_ns) / 1e9
        for record in status.records:
            by_class.setdefault(record.priority, []).append(record)
    for level in sorted(by_class):
        records = by_class[level]
        valid = [r for r in records if r.valid]
        errors = len(records) - len(valid)
        label = ("priority %d" % level) if level else "unclassed"
        if not valid:
            print("    %s: 0 completed, %d errors" % (label, errors))
            continue
        latencies = np.array([r.latency_ns / 1000.0 for r in valid])
        goodput = len(valid) / (len(records) or 1) * 100.0
        print("    %s: %.2f infer/sec, p50 %.0f us, p99 %.0f us, "
              "%d errors (goodput %.1f%%)"
              % (label, len(valid) / window_s if window_s else 0.0,
                 float(np.percentile(latencies, 50)),
                 float(np.percentile(latencies, 99)), errors, goodput))
    for status in results:
        for entry in status.server_stats.get("model_stats", []):
            for row in entry.get("priority_stats", []):
                success = int(row.get("success_count", 0))
                queue_ns = int(row.get("queue_ns", 0))
                print("    server %s priority %s (this window): "
                      "%d ok, %d rejected, %d timed out, %d shed, "
                      "mean queue %.0f us"
                      % (entry.get("name", "?"),
                         row.get("priority_level", "?"), success,
                         int(row.get("reject_count", 0)),
                         int(row.get("timeout_count", 0)),
                         int(row.get("shed_count", 0)),
                         queue_ns / success / 1000.0 if success else 0.0))
            for row in entry.get("tenant_stats", []):
                success = int(row.get("success_count", 0))
                duration_ns = int(row.get("duration_ns", 0))
                print("    tenant %s @ %s (this window): %d ok, "
                      "%d quota-rejected, %d failed, mean %.0f us"
                      % (row.get("tenant", "?"),
                         entry.get("name", "?"), success,
                         int(row.get("reject_count", 0)),
                         int(row.get("fail_count", 0)),
                         duration_ns / success / 1000.0 if success
                         else 0.0))
    # Per-tenant latency DISTRIBUTIONS from the scraped
    # tpu_tenant_request_duration_us histogram (the family that used
    # to be a sum-only counter — now p50/p99 are estimable).
    from client_tpu.perf.metrics_manager import histogram_quantiles

    for status in results:
        quantiles = histogram_quantiles(status.tpu_metrics)
        for key in sorted(k for k in quantiles
                          if k.startswith("tenant_request_duration_us|")):
            tenant = key.split("|", 1)[1]
            q = quantiles[key]
            print("    tenant %s histogram (this window): p50 %.0f us, "
                  "p99 %.0f us, mean %.0f us over %d requests"
                  % (tenant, q["p50_us"], q["p99_us"], q["mean_us"],
                     q["count"]))


def print_chaos_report(results: List[PerfStatus], retry_count: int,
                       injected: Optional[dict] = None,
                       description: str = "",
                       unrecovered: int = 0) -> None:
    """The --chaos summary: goodput (successful inferences/sec), the
    client-visible error rate, retry volume, tail latency under fault,
    and — for in-process runs — how many faults were injected vs how
    many escaped retries (the recovery rate the acceptance gate
    regresses on). ``unrecovered`` is robust.exhausted_total(): a
    process-lifetime counter, like the injection counters, so recovery
    accounts for warm-up-window failures that per-window error counts
    would miss."""
    print("Chaos summary (%s):" % (description or "no injection"))
    total_completed = sum(s.completed_count for s in results)
    total_errors = sum(s.error_count for s in results)
    seen = total_completed + total_errors
    for status in results:
        attempted = status.completed_count + status.error_count
        error_rate = (status.error_count / attempted * 100.0
                      if attempted else 0.0)
        print("    goodput %.2f infer/sec, error rate %.2f%% "
              "(%d/%d), p99 %.0f usec"
              % (status.throughput, error_rate, status.error_count,
                 attempted, status.latency_percentiles.get(99, 0.0)))
    print("    client retries: %d" % retry_count)
    if injected:
        faults = injected.get("injected_errors", 0) \
            + injected.get("injected_drops", 0)
        print("    injected: %d errors, %d drops, %d delayed requests"
              % (injected.get("injected_errors", 0),
                 injected.get("injected_drops", 0),
                 injected.get("delayed_requests", 0)))
        if faults:
            recovered = max(faults - unrecovered, 0)
            print("    recovered %d/%d injected faults (%.1f%%) across "
                  "%d client-visible results"
                  % (recovered, faults, recovered / faults * 100.0, seen))


def print_failover_report(results: List[PerfStatus],
                          fleet_totals: dict,
                          pool_stats: Optional[dict] = None,
                          description: str = "") -> None:
    """The multi-endpoint summary: goodput across the fleet,
    client-visible errors (the zero that proves failover masked an
    outage), hedge volume vs budget, and per-endpoint health at the
    end of the run. ``fleet_totals`` is robust.fleet_totals()
    (process-lifetime, like the retry counters); ``pool_stats`` is the
    shared pool's stats() snapshot when one pool spanned the run."""
    print("Failover summary (%s):" % (description or "endpoint pool"))
    total_completed = sum(s.completed_count for s in results)
    total_errors = sum(s.error_count for s in results)
    attempted = total_completed + total_errors
    goodput_pct = (total_completed / attempted * 100.0) if attempted else 0.0
    print("    client-visible errors: %d of %d requests "
          "(goodput %.1f%%)" % (total_errors, attempted, goodput_pct))
    requests = pool_stats.get("requests", attempted) if pool_stats \
        else attempted
    hedge_ratio = (fleet_totals.get("hedges_fired", 0) / requests * 100.0
                   if requests else 0.0)
    print("    failovers: %d, hedges fired: %d (%.2f%% of requests), "
          "hedges won: %d"
          % (fleet_totals.get("failovers", 0),
             fleet_totals.get("hedges_fired", 0), hedge_ratio,
             fleet_totals.get("hedges_won", 0)))
    print("    ejections: %d, readmissions: %d"
          % (fleet_totals.get("ejections", 0),
             fleet_totals.get("readmissions", 0)))
    if pool_stats:
        if pool_stats.get("hedge_delay_ms") is not None:
            print("    hedge delay: %.1f ms (observed latency "
                  "quantile)" % pool_stats["hedge_delay_ms"])
        for endpoint in pool_stats.get("endpoints", ()):
            print("    endpoint %s: %s, %d requests, %d failures, "
                  "ewma latency %.1f ms"
                  % (endpoint["url"], endpoint["state"],
                     endpoint["requests"], endpoint["failures"],
                     endpoint["ewma_latency_ms"]))


def write_csv(path: str, results: List[PerfStatus],
              mode: str = "concurrency") -> None:
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow([
            "Concurrency" if mode == "concurrency" else "Request Rate",
            "Inferences/Second", "p50 latency", "p90 latency",
            "p95 latency", "p99 latency", "Avg latency", "Std latency",
            "Completed", "Delayed", "Errors",
            "Avg HBM Used (MiB)", "Max HBM Used (MiB)",
            "Avg HBM Utilization",
        ])
        for status in results:
            hbm = status.tpu_metrics.get("hbm_used_bytes", {})
            util = status.tpu_metrics.get("hbm_utilization", {})
            writer.writerow([
                status.concurrency if mode == "concurrency"
                else status.request_rate,
                round(status.throughput, 2),
                round(status.latency_percentiles.get(50, 0), 1),
                round(status.latency_percentiles.get(90, 0), 1),
                round(status.latency_percentiles.get(95, 0), 1),
                round(status.latency_percentiles.get(99, 0), 1),
                round(status.avg_latency_us, 1),
                round(status.std_latency_us, 1),
                status.completed_count,
                status.delayed_count,
                status.error_count,
                round(hbm.get("avg", 0) / 2**20, 2) if hbm else "",
                round(hbm.get("max", 0) / 2**20, 2) if hbm else "",
                round(util.get("avg", 0), 4) if util else "",
            ])


def export_profile(path: str, results: List[PerfStatus], model_name: str,
                   service_kind: str = "triton", endpoint: str = "",
                   mode: str = "concurrency") -> None:
    """The profile-export JSON the LLM metrics layer parses (same
    experiment/requests shape as the reference exporter)."""
    experiments = []
    for status in results:
        requests = []
        for record in status.records:
            if not record.valid:
                continue
            requests.append({
                "timestamp": record.start_ns,
                "response_timestamps": list(record.end_ns),
            })
        experiments.append({
            "experiment": {
                "mode": mode,
                "value": (
                    status.concurrency if mode == "concurrency"
                    else status.request_rate
                ),
            },
            "requests": requests,
            "window_boundaries": [status.window_start_ns,
                                  status.window_end_ns],
        })
    doc = {
        "version": "0.1",
        "service_kind": service_kind,
        "endpoint": endpoint,
        "model": model_name,
        "experiments": experiments,
    }
    with open(path, "w") as f:
        json.dump(doc, f)
