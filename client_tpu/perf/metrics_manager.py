"""Server metrics collection for the perf harness.

Parity with the reference MetricsManager (metrics_manager.h:56-82,
metrics.h:37-43): poll the server's Prometheus ``/metrics`` endpoint on
a background thread every ``metrics_interval_ms`` and parse accelerator
gauges into per-window :class:`TpuMetrics` snapshots. The DCGM GPU
util/power/memory maps become TPU HBM gauges (tpu_hbm_used_bytes /
tpu_hbm_total_bytes / tpu_hbm_utilization exported by the in-repo
server; any Prometheus source with those families works).
"""

from __future__ import annotations

import re
import threading
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)')
_LABEL = re.compile(r'(\w+)="([^"]*)"')


@dataclass
class TpuMetrics:
    """One scrape: per-device gauge maps keyed by device uuid
    (parity: Metrics::gpu_utilization_per_gpu etc, metrics.h:37-43),
    plus the dynamic-batcher pipeline gauges keyed by model name."""

    hbm_used_bytes: Dict[str, float] = field(default_factory=dict)
    hbm_total_bytes: Dict[str, float] = field(default_factory=dict)
    hbm_utilization: Dict[str, float] = field(default_factory=dict)
    # Device-axis families (server/devstats.py): the per-model HBM
    # ledger keyed "model|c<component>", busy-time counters and the
    # duty-cycle gauge keyed by device uuid, compile counters keyed
    # "model|b<shape-fingerprint>".
    hbm_model_bytes: Dict[str, float] = field(default_factory=dict)
    # HBM-allocator families (server/hbm.py): free-budget gauge per
    # device uuid, eviction counters keyed
    # "model|c<component>|g<reason>", page-out counters per model; the
    # restore-latency histogram lands in ``histograms``.
    hbm_free_bytes: Dict[str, float] = field(default_factory=dict)
    hbm_evictions_total: Dict[str, float] = field(default_factory=dict)
    weight_pageout_total: Dict[str, float] = field(default_factory=dict)
    device_busy_us_total: Dict[str, float] = field(default_factory=dict)
    device_duty_cycle: Dict[str, float] = field(default_factory=dict)
    compile_total: Dict[str, float] = field(default_factory=dict)
    device_stats_errors_total: Dict[str, float] = field(
        default_factory=dict)
    batch_pending_depth: Dict[str, float] = field(default_factory=dict)
    batch_inflight: Dict[str, float] = field(default_factory=dict)
    batch_queue_delay_us: Dict[str, float] = field(default_factory=dict)
    batch_overlap_ratio: Dict[str, float] = field(default_factory=dict)
    sequence_active: Dict[str, float] = field(default_factory=dict)
    sequence_backlog: Dict[str, float] = field(default_factory=dict)
    cache_hit_total: Dict[str, float] = field(default_factory=dict)
    cache_miss_total: Dict[str, float] = field(default_factory=dict)
    cache_size_bytes: Dict[str, float] = field(default_factory=dict)
    cache_entries: Dict[str, float] = field(default_factory=dict)
    cache_evictions_total: Dict[str, float] = field(default_factory=dict)
    # QoS families: priority queue depths keyed "model|p<level>", shed
    # counters likewise; tenant counters keyed by tenant label.
    priority_queue_size: Dict[str, float] = field(default_factory=dict)
    shed_total: Dict[str, float] = field(default_factory=dict)
    tenant_success_total: Dict[str, float] = field(default_factory=dict)
    tenant_rejected_total: Dict[str, float] = field(default_factory=dict)
    # Replica-serving families: health gauges per model, lifecycle
    # counters per model, cumulative exec time keyed "model|r<index>".
    replica_healthy: Dict[str, float] = field(default_factory=dict)
    replica_count: Dict[str, float] = field(default_factory=dict)
    replica_ejected_total: Dict[str, float] = field(default_factory=dict)
    replica_readmitted_total: Dict[str, float] = field(
        default_factory=dict)
    replica_redispatch_total: Dict[str, float] = field(
        default_factory=dict)
    replica_exec_us: Dict[str, float] = field(default_factory=dict)
    # Autoscale-controller families: desired-fleet gauge per model,
    # decision counters keyed "model|d<direction>|g<reason>", and the
    # replica-seconds cost counter per model (the number the autoscale
    # smoke gates against a max-scale-always baseline).
    replica_desired: Dict[str, float] = field(default_factory=dict)
    scale_events_total: Dict[str, float] = field(default_factory=dict)
    replica_seconds_total: Dict[str, float] = field(
        default_factory=dict)
    # Latency-histogram families (telemetry layer): attr -> series key
    # -> {le_bound: cumulative_count}. Keys are the model (stage
    # histograms append "|s<stage>", tenant histograms use the tenant
    # label); bounds are floats with +Inf as float("inf"). The paired
    # _sum/_count series land in hist_sum/hist_count under the same
    # (attr, key).
    histograms: Dict[str, Dict[str, Dict[float, float]]] = field(
        default_factory=dict)
    hist_sum: Dict[str, Dict[str, float]] = field(default_factory=dict)
    hist_count: Dict[str, Dict[str, float]] = field(default_factory=dict)
    stream_responses_total: Dict[str, float] = field(
        default_factory=dict)
    # Paged-KV-cache families (docs/llm_serving.md): pool occupancy
    # gauges per model, prefix-hit / prefill-chunk counters.
    kv_pages_used: Dict[str, float] = field(default_factory=dict)
    kv_pages_total: Dict[str, float] = field(default_factory=dict)
    kv_prefix_hits_total: Dict[str, float] = field(default_factory=dict)
    prefill_chunks_total: Dict[str, float] = field(default_factory=dict)
    # SLO families (server/slo.py): targets keyed "model|o<objective>",
    # burn rates keyed "model|w<window>", budget/verdict per model —
    # the perf --slo compliance gate and report line read these.
    slo_target: Dict[str, float] = field(default_factory=dict)
    slo_burn_rate: Dict[str, float] = field(default_factory=dict)
    slo_budget_remaining: Dict[str, float] = field(default_factory=dict)
    slo_healthy: Dict[str, float] = field(default_factory=dict)
    # Ensemble-dataflow families (docs/ensembles.md): fused-dispatch
    # and subgraph cache-hit counters per ensemble; the per-stage
    # duration histogram lands in ``histograms`` keyed
    # "model|s<step>".
    ensemble_fused_total: Dict[str, float] = field(default_factory=dict)
    ensemble_cache_hits_total: Dict[str, float] = field(
        default_factory=dict)


_FAMILIES = {
    "tpu_hbm_used_bytes": "hbm_used_bytes",
    "tpu_hbm_total_bytes": "hbm_total_bytes",
    "tpu_hbm_utilization": "hbm_utilization",
    "tpu_hbm_model_bytes": "hbm_model_bytes",
    "tpu_hbm_free_bytes": "hbm_free_bytes",
    "tpu_hbm_evictions_total": "hbm_evictions_total",
    "tpu_weight_pageout_total": "weight_pageout_total",
    "tpu_device_busy_us_total": "device_busy_us_total",
    "tpu_device_duty_cycle": "device_duty_cycle",
    "tpu_compile_total": "compile_total",
    "tpu_device_stats_errors_total": "device_stats_errors_total",
    "tpu_batch_pending_depth": "batch_pending_depth",
    "tpu_batch_inflight": "batch_inflight",
    "tpu_batch_queue_delay_us": "batch_queue_delay_us",
    "tpu_batch_overlap_ratio": "batch_overlap_ratio",
    "tpu_sequence_active": "sequence_active",
    "tpu_sequence_backlog": "sequence_backlog",
    "tpu_cache_hit_total": "cache_hit_total",
    "tpu_cache_miss_total": "cache_miss_total",
    "tpu_cache_size_bytes": "cache_size_bytes",
    "tpu_cache_entries": "cache_entries",
    "tpu_cache_evictions_total": "cache_evictions_total",
    "tpu_priority_queue_size": "priority_queue_size",
    "tpu_shed_total": "shed_total",
    "tpu_tenant_success_total": "tenant_success_total",
    "tpu_tenant_rejected_total": "tenant_rejected_total",
    "tpu_replica_healthy": "replica_healthy",
    "tpu_replica_count": "replica_count",
    "tpu_replica_ejected_total": "replica_ejected_total",
    "tpu_replica_readmitted_total": "replica_readmitted_total",
    "tpu_replica_redispatch_total": "replica_redispatch_total",
    "tpu_replica_exec_us": "replica_exec_us",
    "tpu_replica_desired": "replica_desired",
    "tpu_scale_events_total": "scale_events_total",
    "tpu_replica_seconds_total": "replica_seconds_total",
    "tpu_stream_responses_total": "stream_responses_total",
    "tpu_kv_pages_used": "kv_pages_used",
    "tpu_kv_pages_total": "kv_pages_total",
    "tpu_kv_prefix_hits_total": "kv_prefix_hits_total",
    "tpu_prefill_chunks_total": "prefill_chunks_total",
    "tpu_slo_target": "slo_target",
    "tpu_slo_burn_rate": "slo_burn_rate",
    "tpu_slo_budget_remaining": "slo_budget_remaining",
    "tpu_slo_healthy": "slo_healthy",
    "tpu_ensemble_fused_total": "ensemble_fused_total",
    "tpu_ensemble_cache_hits_total": "ensemble_cache_hits_total",
}

# Histogram families (telemetry layer): the scraper folds their
# ``_bucket`` / ``_sum`` / ``_count`` child series into
# TpuMetrics.histograms / hist_sum / hist_count so the window summary
# can difference cumulative bucket counts and estimate p50/p99 via
# client_tpu.server.telemetry.estimate_quantile.
_HIST_FAMILIES = {
    "tpu_request_duration_us": "request_duration_us",
    "tpu_stage_duration_us": "stage_duration_us",
    "tpu_stream_first_response_us": "stream_first_response_us",
    "tpu_stream_inter_response_us": "stream_inter_response_us",
    "tpu_tenant_request_duration_us": "tenant_request_duration_us",
    "tpu_compile_duration_us": "compile_duration_us",
    "tpu_ensemble_step_duration_us": "ensemble_step_duration_us",
    "tpu_weight_restore_us": "weight_restore_us",
}

# Monotonic counters among the scraped families: summarize_metrics
# reports their within-window DELTA (last - first, clamped at 0 for
# counter resets) instead of a meaningless avg/max of the cumulative
# value. Everything else is a gauge (avg/max of point-in-time values).
_COUNTER_FAMILIES = frozenset((
    "cache_hit_total", "cache_miss_total", "cache_evictions_total",
    "shed_total", "tenant_success_total", "tenant_rejected_total",
    "replica_ejected_total", "replica_readmitted_total",
    "replica_redispatch_total", "replica_exec_us",
    "scale_events_total", "replica_seconds_total",
    "stream_responses_total",
    "kv_prefix_hits_total", "prefill_chunks_total",
    "device_busy_us_total", "compile_total",
    "device_stats_errors_total",
    "ensemble_fused_total", "ensemble_cache_hits_total",
    "hbm_evictions_total", "weight_pageout_total",
))


def _histogram_parts(family: str):
    """(attr, kind) for a histogram child sample name, else None —
    kind is "bucket", "sum" or "count"."""
    for suffix in ("_bucket", "_sum", "_count"):
        if family.endswith(suffix):
            base = family[: -len(suffix)]
            attr = _HIST_FAMILIES.get(base)
            if attr is not None:
                return attr, suffix[1:]
    return None


def _hist_key(attr: str, labels: Dict[str, str]) -> str:
    """Series key for one histogram label set: model or tenant, with
    the stage folded in as a compound "model|s<stage>" key so deltas
    and quantiles stay per stage."""
    key = (labels.get("model") or labels.get("tenant") or "0")
    if "stage" in labels:
        key = "%s|s%s" % (key, labels["stage"])
    # Ensemble-step histograms carry a step label instead of a stage;
    # fold it the same way so quantiles stay per composing step.
    if "step" in labels:
        key = "%s|s%s" % (key, labels["step"])
    return key


def parse_prometheus(text: str) -> TpuMetrics:
    metrics = TpuMetrics()
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        hist = _histogram_parts(m.group("name"))
        if hist is not None:
            attr, kind = hist
            labels = dict(_LABEL.findall(m.group("labels") or ""))
            try:
                value = float(m.group("value"))
            except ValueError:
                continue
            key = _hist_key(attr, labels)
            if kind == "bucket":
                le = labels.get("le", "")
                try:
                    bound = float("inf") if le == "+Inf" else float(le)
                except ValueError:
                    continue
                metrics.histograms.setdefault(attr, {}).setdefault(
                    key, {})[bound] = value
            elif kind == "sum":
                metrics.hist_sum.setdefault(attr, {})[key] = value
            else:
                metrics.hist_count.setdefault(attr, {})[key] = value
            continue
        if m.group("name") not in _FAMILIES:
            continue
        labels = dict(_LABEL.findall(m.group("labels") or ""))
        # Batcher gauges are per-model; HBM gauges are per-device;
        # tenant counters per tenant; priority families carry a
        # compound model|p<level> key so deltas stay per class, and
        # replica exec time a model|r<index> key so deltas stay per
        # fault domain.
        key = (labels.get("model") or labels.get("tenant")
               or labels.get("tpu_uuid") or labels.get("gpu_uuid")
               or labels.get("device") or "0")
        if "priority" in labels:
            key = "%s|p%s" % (key, labels["priority"])
        if "replica" in labels:
            key = "%s|r%s" % (key, labels["replica"])
        if "component" in labels:
            key = "%s|c%s" % (key, labels["component"])
        if "shape" in labels:
            key = "%s|b%s" % (key, labels["shape"])
        if "window" in labels:
            key = "%s|w%s" % (key, labels["window"])
        if "objective" in labels:
            key = "%s|o%s" % (key, labels["objective"])
        if "direction" in labels:
            key = "%s|d%s" % (key, labels["direction"])
        if "reason" in labels:
            key = "%s|g%s" % (key, labels["reason"])
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        getattr(metrics, _FAMILIES[m.group("name")])[key] = value
    return metrics


class MetricsManager:
    """Polls ``url`` every ``metrics_interval_ms`` while started;
    snapshots accumulate until :meth:`get_and_reset`."""

    def __init__(self, url: str, metrics_interval_ms: float = 1000.0,
                 timeout_s: float = 2.0):
        if "://" not in url:
            url = "http://" + url
        if not url.endswith("/metrics"):
            url = url.rstrip("/") + "/metrics"
        self._url = url
        self._interval_s = metrics_interval_ms / 1000.0
        self._timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._snapshots: List[TpuMetrics] = []
        self.scrape_failures = 0

    def scrape_text(self) -> str:
        """One raw exposition scrape (the genai front-end brackets its
        run with two of these; parse is the caller's business)."""
        with urllib.request.urlopen(self._url,
                                    timeout=self._timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def scrape_once(self) -> TpuMetrics:
        return parse_prometheus(self.scrape_text())

    def check_reachable(self) -> None:
        """Raise if the endpoint cannot be scraped (parity:
        CheckForMissingMetrics fail-fast before profiling)."""
        self.scrape_once()

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._poll, daemon=True)
        self._thread.start()

    def _poll(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                snapshot = self.scrape_once()
            except Exception:
                self.scrape_failures += 1
                continue
            with self._lock:
                self._snapshots.append(snapshot)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def get_and_reset(self) -> List[TpuMetrics]:
        """Snapshots collected since the last call (one measurement
        window's worth)."""
        with self._lock:
            out = self._snapshots
            self._snapshots = []
        return out


def summarize_metrics(snapshots: List[TpuMetrics]) -> Dict[str, Dict[str, float]]:
    """Per-family window summary. Gauges get avg/max across the
    window's snapshots, averaged over devices (what the CSV 'GPU
    metrics' columns become; the batch_*/cache gauge families average
    over models instead). Counter families (_COUNTER_FAMILIES) get the
    window DELTA instead — first-to-last difference summed over
    models, clamped at 0 per model so a server restart mid-window
    cannot go negative."""
    out: Dict[str, Dict[str, float]] = {}
    for attr in ("hbm_used_bytes", "hbm_total_bytes", "hbm_utilization",
                 "hbm_free_bytes",
                 "batch_pending_depth", "batch_inflight",
                 "batch_queue_delay_us", "batch_overlap_ratio",
                 "sequence_active", "sequence_backlog",
                 "cache_size_bytes", "cache_entries",
                 "priority_queue_size", "replica_healthy",
                 "replica_count", "replica_desired",
                 "kv_pages_used", "kv_pages_total",
                 "device_duty_cycle"):
        values = []
        for snap in snapshots:
            per_device = getattr(snap, attr)
            if per_device:
                values.append(sum(per_device.values()) / len(per_device))
        if values:
            out[attr] = {
                "avg": sum(values) / len(values),
                "max": max(values),
            }
    # Gauge-aware window deltas for the fleet-size gauges: how the
    # value MOVED across the window (signed first-to-last, summed over
    # models) — avg/max alone cannot show that an autoscaled fleet
    # grew then shrank back. min tracks the window trough.
    for attr in ("replica_count", "replica_desired", "replica_healthy"):
        first: Dict[str, float] = {}
        last: Dict[str, float] = {}
        low: Dict[str, float] = {}
        for snap in snapshots:
            for key, value in getattr(snap, attr).items():
                first.setdefault(key, value)
                last[key] = value
                low[key] = min(low.get(key, value), value)
        if last and attr in out:
            out[attr]["delta"] = sum(last[k] - first[k] for k in last)
            out[attr]["min"] = sum(low.values())
    # The per-model HBM ledger sums over its (model, component) rows
    # per snapshot — the total attributed bytes is the meaningful
    # aggregate (a mean over rows is not), and its max is the window's
    # attributed-HBM peak. The unattributed/residual row is EXCLUDED:
    # it closes the gap to tpu_hbm_used_bytes by construction, so
    # including it would make this line a duplicate of whole-chip
    # used bytes instead of what the ledger attributed.
    values = []
    for snap in snapshots:
        attributed = sum(
            value for key, value in snap.hbm_model_bytes.items()
            if not key.startswith("unattributed|"))
        if attributed:
            values.append(attributed)
    if values:
        out["hbm_model_bytes"] = {
            "avg": sum(values) / len(values),
            "max": max(values),
        }
    for attr in sorted(_COUNTER_FAMILIES):
        first: Dict[str, float] = {}
        last: Dict[str, float] = {}
        for snap in snapshots:
            for key, value in getattr(snap, attr).items():
                first.setdefault(key, value)
                last[key] = value
        if last:
            out[attr] = {
                "delta": sum(max(last[k] - first.get(k, 0.0), 0.0)
                             for k in last),
                "last": sum(last.values()),
            }
    out.update(_summarize_histograms(snapshots))
    return out


def _summarize_histograms(snapshots: List[TpuMetrics]
                          ) -> Dict[str, Dict[str, float]]:
    """Window deltas of the cumulative histogram series, flattened to
    ``hist!<attr>|<key>|le=<bound>`` / ``...|sum`` / ``...|count``
    entries. Differencing cumulative-in-le bucket counts yields the
    WINDOW's cumulative distribution, so the entries stay additive —
    the profiler's merge can sum them across stable windows and
    :func:`histogram_quantiles` re-estimates p50/p99 from the sums."""
    from client_tpu.server.telemetry import format_le

    out: Dict[str, Dict[str, float]] = {}
    first_b: Dict[tuple, float] = {}
    last_b: Dict[tuple, float] = {}
    first_sc: Dict[tuple, float] = {}
    last_sc: Dict[tuple, float] = {}
    for index, snap in enumerate(snapshots):
        for attr, by_key in snap.histograms.items():
            for key, buckets in by_key.items():
                for bound, value in buckets.items():
                    entry = (attr, key, bound)
                    # Baseline comes from the FIRST snapshot only: a
                    # series born mid-window (model's first traffic
                    # after the window opened) starts from 0, not from
                    # its first observed cumulative value — otherwise
                    # its whole delta would vanish.
                    if index == 0:
                        first_b.setdefault(entry, value)
                    last_b[entry] = value
        for attr, by_key in snap.hist_sum.items():
            for key, value in by_key.items():
                entry = (attr, key, "sum")
                if index == 0:
                    first_sc.setdefault(entry, value)
                last_sc[entry] = value
        for attr, by_key in snap.hist_count.items():
            for key, value in by_key.items():
                entry = (attr, key, "count")
                if index == 0:
                    first_sc.setdefault(entry, value)
                last_sc[entry] = value
    # Only series whose count moved this window are emitted: idle
    # models' zero-delta ladders would bloat every summary.
    active = {
        (attr, key)
        for (attr, key, which), value in last_sc.items()
        if which == "count"
        and value - first_sc.get((attr, key, which), 0.0) > 0
    }
    for (attr, key, bound), value in last_b.items():
        if (attr, key) not in active:
            continue
        delta = max(value - first_b.get((attr, key, bound), 0.0), 0.0)
        out["hist!%s|%s|le=%s" % (attr, key, format_le(bound))] = {
            "delta": delta}
    for (attr, key, which), value in last_sc.items():
        if (attr, key) not in active:
            continue
        delta = max(value - first_sc.get((attr, key, which), 0.0), 0.0)
        out["hist!%s|%s|%s" % (attr, key, which)] = {"delta": delta}
    return out


def histogram_quantiles(tpu_metrics: Dict[str, Dict[str, float]]
                        ) -> Dict[str, Dict[str, float]]:
    """Bucket-quantile estimates from a window summary (or a merge of
    summaries): ``{"<attr>|<key>": {"p50_us", "p99_us", "mean_us",
    "count"}}``. Input entries are the ``hist!`` rows
    :func:`_summarize_histograms` emits."""
    from client_tpu.server.telemetry import estimate_quantile

    grouped: Dict[str, Dict[str, float]] = {}
    for name, entry in tpu_metrics.items():
        if not name.startswith("hist!"):
            continue
        body = name[len("hist!"):]
        attr_key, part = body.rsplit("|", 1)
        grouped.setdefault(attr_key, {})[part] = entry.get("delta", 0.0)
    out: Dict[str, Dict[str, float]] = {}
    for attr_key, parts in grouped.items():
        count = parts.get("count", 0.0)
        if count <= 0:
            continue
        buckets = []
        for part, value in parts.items():
            if not part.startswith("le="):
                continue
            le = part[3:]
            bound = float("inf") if le == "+Inf" else float(le)
            buckets.append((bound, value))
        if not buckets:
            continue
        total = parts.get("sum", 0.0)
        out[attr_key] = {
            "p50_us": estimate_quantile(buckets, 0.50),
            "p99_us": estimate_quantile(buckets, 0.99),
            "mean_us": total / count if count else 0.0,
            "count": count,
        }
    return out
