"""perf CLI — the perf_analyzer front door.

Run:  python -m client_tpu.perf -m simple -u localhost:8001 \
          --concurrency-range 1:4 --shared-memory tpu

Flag set mirrors the reference command_line_parser.h:45-176 surface
(the subset implemented so far; unknown reference flags fail loudly
rather than silently no-op).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from client_tpu.perf.client_backend import BackendKind, ClientBackendFactory
from client_tpu.perf.data_loader import DataLoader
from client_tpu.perf.load_manager import (
    ConcurrencyManager,
    CustomLoadManager,
    InferDataManager,
    PeriodicConcurrencyManager,
    RequestRateManager,
    SequenceManager,
)
from client_tpu.perf.metrics_manager import MetricsManager
from client_tpu.perf.model_parser import ModelParser, SchedulerType
from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig
from client_tpu.perf.report import export_profile, print_report, write_csv
from client_tpu.utils import InferenceServerException


def _parse_range(value: str, cast=int):
    """start[:end[:step]]"""
    parts = value.split(":")
    start = cast(parts[0])
    end = cast(parts[1]) if len(parts) > 1 else start
    step = cast(parts[2]) if len(parts) > 2 else cast(1)
    return start, end, step


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="client_tpu.perf", description="TPU-native perf analyzer"
    )
    parser.add_argument("-m", "--model-name", required=True)
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-u", "--url", default="localhost:8001",
                        help="server endpoint, or a comma-separated "
                             "endpoint list: the client then routes "
                             "by expected completion time across "
                             "healthy endpoints with failover + "
                             "hedging (service-kind triton only)")
    parser.add_argument("-i", "--protocol", choices=["grpc", "http"],
                        default="grpc")
    parser.add_argument("--service-kind", default="triton",
                        choices=["triton", "inprocess", "openai",
                                 "torchserve", "tfserving"])
    parser.add_argument("--endpoint", default="v1/chat/completions",
                        help="openai service-kind request path")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("--async", dest="async_mode", action="store_true",
                        default=True)
    parser.add_argument("--sync", dest="async_mode", action="store_false")
    parser.add_argument("--streaming", action="store_true")
    parser.add_argument("--max-threads", type=int, default=16)

    parser.add_argument("--concurrency-range", default=None,
                        help="start:end:step")
    parser.add_argument("--request-rate-range", default=None,
                        help="start:end:step")
    parser.add_argument("--request-intervals", default=None,
                        help="file with one interval (us) per line")
    parser.add_argument("--periodic-concurrency-range", default=None,
                        help="start:end:step (LLM ramp mode)")
    parser.add_argument("--request-period", type=int, default=10)
    parser.add_argument("--request-distribution", default="constant",
                        choices=["constant", "poisson"])

    parser.add_argument("-p", "--measurement-interval", type=int,
                        default=5000, help="window ms")
    parser.add_argument("--measurement-mode", default="time_windows",
                        choices=["time_windows", "count_windows"])
    parser.add_argument("--measurement-request-count", type=int, default=50)
    parser.add_argument("--request-count", type=int, default=0,
                        help="measure exactly N requests in one window "
                             "(single-trial by design; parity: the "
                             "reference's --request-count)")
    parser.add_argument("-r", "--max-trials", type=int, default=10)
    parser.add_argument("-s", "--stability-percentage", type=float,
                        default=10.0)
    parser.add_argument("-l", "--latency-threshold", type=float, default=0.0,
                        help="ms; 0 = no limit")
    parser.add_argument("--percentile", type=int, default=0)

    parser.add_argument("--shared-memory", default="none",
                        choices=["none", "system", "tpu"])
    parser.add_argument("--output-shared-memory-size", type=int,
                        default=102400)
    parser.add_argument("--tpu-arena-url", default="",
                        help="arena service url (defaults to --url for grpc)")

    parser.add_argument("--input-data", default="random",
                        help="random | zero | path/to/data.json")
    parser.add_argument("--string-length", type=int, default=16)
    parser.add_argument("--string-data", default=None)
    parser.add_argument("--shape", action="append", default=[],
                        help="name:d1,d2 overrides for variable dims")
    parser.add_argument("--bls-composing-models", default="",
                        help="comma-separated models a BLS/pipeline model "
                             "calls; their server stats are paired with "
                             "the top model's per window")

    parser.add_argument("--sequence-length", type=int, default=20)
    parser.add_argument("--sequence-length-variation", type=float,
                        default=20.0)
    parser.add_argument("--sequence-id-range", default=None,
                        help="start[:end]")

    parser.add_argument("-f", "--latency-report-file", default=None)
    parser.add_argument("--profile-export-file", default=None)

    parser.add_argument("--trace", type=int, default=0, metavar="RATE",
                        help="enable server-side span tracing at "
                             "1-in-RATE sampling (1 = every request), "
                             "harvest the trace file after the run, and "
                             "print the stage-attribution table "
                             "(decode/cache/queue/execute/fetch/encode "
                             "p50/p99 + share of server time). "
                             "service-kind triton and inprocess only; "
                             "for remote servers the trace file path "
                             "must be reachable from this process")
    parser.add_argument("--trace-file", default=None,
                        help="span trace output path (default: a "
                             "temp file, deleted after the report)")
    parser.add_argument("--slo", action="store_true",
                        help="assert SLO compliance after the run from "
                             "the scraped tpu_slo_* families and exit "
                             "non-zero on violation (CI-friendly); "
                             "prints the per-model SLO/burn-rate "
                             "summary. Needs a model declaring an "
                             "`slo` block; remote servers need "
                             "--collect-metrics / a reachable "
                             "--metrics-url")
    parser.add_argument("--slo-strict", action="store_true",
                        help="with --slo, also fail when any fast-"
                             "window burn rate exceeds 1 (not just on "
                             "the multi-window unhealthy verdict)")
    parser.add_argument("--collect-metrics", action="store_true",
                        help="scrape server Prometheus metrics per window")
    parser.add_argument("--metrics-url", default=None,
                        help="defaults to http://<host>:8000/metrics")
    parser.add_argument("--metrics-interval", type=float, default=1000.0,
                        help="scrape interval ms")

    parser.add_argument("--chaos", default=None,
                        help="fault-injection spec, e.g. "
                             "'latency_ms=50,error_rate=0.1,drop_rate=0.01,"
                             "seed=7'. Configures server-side chaos for "
                             "--service-kind inprocess; remote servers "
                             "must set CLIENT_TPU_CHAOS themselves. "
                             "Enables the chaos summary report.")
    parser.add_argument("--retries", type=int, default=None,
                        help="max client-side retries per request "
                             "(default 0; 3 under --chaos)")
    parser.add_argument("--retry-backoff-ms", type=float, default=25.0,
                        help="initial retry backoff (exponential, full "
                             "jitter)")
    parser.add_argument("--circuit-breaker-threshold", type=int, default=0,
                        help="consecutive failures before a worker's "
                             "circuit opens (0 = no breaker)")
    parser.add_argument("--hedge-delay-ms", type=float, default=1.0,
                        help="floor for the hedge delay; the actual "
                             "delay is max(this, the pool's observed "
                             "p95 latency). Applies to multi-endpoint "
                             "runs only")
    parser.add_argument("--hedge-max-ratio", type=float, default=0.05,
                        help="hedge budget: max fraction of requests "
                             "that may fire a hedge (0 disables "
                             "hedging)")
    parser.add_argument("--fleet", type=int, default=0,
                        help="start N embedded servers (each its own "
                             "core, --protocol transport) and spread "
                             "-u across them — the self-contained "
                             "failover/hedging testbed (service-kind "
                             "triton only)")
    parser.add_argument("--degrade-one",
                        default=None,
                        help="staged degradation of one fleet member: "
                             "'latency_ms=200,latency_after_s=1,"
                             "kill_after_s=3,victim=0' (requires "
                             "--fleet)")
    parser.add_argument("--priority-mix", default=None,
                        help="weighted priority classes for issued "
                             "requests, e.g. '1:0.2,2:0.8' (level:"
                             "weight; 1 = highest). Enables the "
                             "per-class QoS summary report")
    parser.add_argument("--tenant", default=None,
                        help="tenant identity stamped on every "
                             "request (the `tenant` parameter; "
                             "per-tenant quotas and accounting key "
                             "on it)")
    parser.add_argument("--overload", default=None,
                        help="staged burst-arrival injection against "
                             "the model under test: 'rate=500,"
                             "after_s=1,duration_s=3,workers=8,"
                             "seed=11,priority=2,tenant=bulk' — the "
                             "burst saturates the queue while the "
                             "foreground load's QoS is measured "
                             "(service-kind inprocess and triton). "
                             "A 'trace=rate:dur+rate:dur+...' spec "
                             "(optional 'repeat=N') replays a "
                             "multi-stage diurnal schedule instead "
                             "of one burst — the autoscale "
                             "controller's test surface (rate 0 "
                             "stages are idle gaps)")
    return parser


def run(argv: Optional[List[str]] = None, core=None) -> int:
    args = build_parser().parse_args(argv)

    # Robustness wiring: retries default on under chaos (measuring
    # recovery is the point), off otherwise.
    from client_tpu import robust

    retries = args.retries if args.retries is not None \
        else (3 if args.chaos else 0)
    retry_policy = None
    if retries > 0:
        retry_policy = robust.RetryPolicy(
            max_attempts=retries + 1,
            initial_backoff_s=args.retry_backoff_ms / 1000.0)
    breaker_factory = None
    if args.circuit_breaker_threshold > 0:
        threshold = args.circuit_breaker_threshold
        breaker_factory = (
            lambda: robust.CircuitBreaker(failure_threshold=threshold))
    robustness = dict(retry_policy=retry_policy,
                      breaker_factory=breaker_factory)
    chaos_config = None
    if args.chaos:
        from client_tpu.server import chaos as chaos_mod

        if args.service_kind == "inprocess":
            chaos_config = chaos_mod.configure_from_spec(args.chaos)
        else:
            # Remote server: injection happens there, not here.
            chaos_config = chaos_mod.ChaosConfig.from_spec(args.chaos)
            print("note: --chaos against a remote server only shapes "
                  "the report; start the server with CLIENT_TPU_CHAOS="
                  "'%s' to inject faults" % args.chaos, file=sys.stderr)
    robust.reset_retry_total()

    if args.service_kind in ("openai", "torchserve", "tfserving") \
            and (retry_policy is not None or breaker_factory is not None):
        print("warning: --retries/--circuit-breaker-threshold are not "
              "supported by the %s backend and will be ignored"
              % args.service_kind, file=sys.stderr)

    # -- embedded fleet: N in-process servers behind real transports --
    fleet_members = []  # (scope, server, core, stop_fn)
    scenario = None
    if args.fleet and args.fleet > 1:
        if args.service_kind != "triton":
            print("perf failed: --fleet requires --service-kind triton",
                  file=sys.stderr)
            return 1
        from client_tpu.server.app import build_core as _build_core
        from client_tpu.server.app import start_grpc_server

        fleet_urls = []
        for i in range(args.fleet):
            scope = "ep%d" % i
            member_core = _build_core([args.model_name])
            member_core.chaos_scope = scope
            if args.protocol == "grpc":
                handle = start_grpc_server(core=member_core,
                                           address="127.0.0.1:0")
                fleet_urls.append(handle.address)
                fleet_members.append((scope, handle, member_core,
                                      handle.stop))
            else:
                from client_tpu.server.http_server import (
                    start_http_server_thread,
                )

                runner = start_http_server_thread(
                    member_core, host="127.0.0.1", port=0)
                fleet_urls.append("127.0.0.1:%d" % runner.port)

                def _stop_http(runner=runner, core=member_core):
                    core.ready = False
                    runner.stop()
                    core.shutdown()

                fleet_members.append((scope, runner, member_core,
                                      _stop_http))
        args.url = ",".join(fleet_urls)
        print("fleet: %d embedded %s servers at %s"
              % (args.fleet, args.protocol, args.url), file=sys.stderr)

    if args.degrade_one is not None and not fleet_members:
        print("perf failed: --degrade-one requires --fleet",
              file=sys.stderr)
        return 1

    # -- endpoint pool: one shared pool spans every worker client -----
    endpoint_urls = robust.EndpointPool.split_url(args.url)
    endpoint_pool = None
    if args.service_kind == "triton" and len(endpoint_urls) > 1:
        endpoint_pool = robust.EndpointPool(
            endpoint_urls,
            breaker_factory=breaker_factory,
            hedge_delay_min_ms=args.hedge_delay_ms,
            hedge_max_ratio=args.hedge_max_ratio,
        )
    elif len(endpoint_urls) > 1:
        print("warning: multi-endpoint -u is only supported for "
              "--service-kind triton; using %s" % endpoint_urls[0],
              file=sys.stderr)
        args.url = endpoint_urls[0]

    if args.service_kind == "openai":
        factory = ClientBackendFactory(
            BackendKind.OPENAI, url=args.url, verbose=args.verbose,
            openai_endpoint=args.endpoint,
        )
    elif args.service_kind in ("torchserve", "tfserving"):
        factory = ClientBackendFactory(
            BackendKind.TORCHSERVE if args.service_kind == "torchserve"
            else BackendKind.TFSERVING,
            url=args.url, verbose=args.verbose,
            # gRPC PredictionService is TF-Serving's native protocol;
            # -i http selects the REST predict API instead.
            tfserving_grpc=args.protocol != "http",
        )
    elif args.service_kind == "inprocess":
        if core is None:
            from client_tpu.server.app import build_core

            core = build_core([args.model_name])
        factory = ClientBackendFactory(BackendKind.IN_PROCESS, core=core,
                                       **robustness)
        if args.shared_memory == "tpu" and core.memory.arena is not None:
            import client_tpu.utils.tpu_shared_memory as tpushm

            tpushm.set_arena(core.memory.arena)
    else:
        kind = (
            BackendKind.TRITON_GRPC if args.protocol == "grpc"
            else BackendKind.TRITON_HTTP
        )
        factory = ClientBackendFactory(kind, url=args.url,
                                       verbose=args.verbose,
                                       endpoint_pool=endpoint_pool,
                                       **robustness)

    setup_backend = factory.create()
    parser_obj = ModelParser()
    try:
        model = parser_obj.parse(
            setup_backend, args.model_name, args.model_version,
            args.batch_size,
            bls_composing_models=[
                m for m in args.bls_composing_models.split(",") if m])
    except InferenceServerException as e:
        print("perf failed: %s" % e, file=sys.stderr)
        setup_backend.close()
        if endpoint_pool is not None:
            endpoint_pool.close()
        for _scope, _server, _core, stop_fn in fleet_members:
            try:
                stop_fn()
            except Exception:
                pass
        return 1
    # variable-dim overrides; name:DTYPE:d1,d2 CREATES the tensor for
    # metadata-less service kinds (tfserving's gRPC surface exposes no
    # KServe metadata)
    for override in args.shape:
        name, _, rest = override.partition(":")
        dtype, _, dims = rest.rpartition(":")
        if dtype:
            from client_tpu.perf.model_parser import ModelTensor

            model.inputs[name] = ModelTensor(
                name, dtype, [int(d) for d in dims.split(",")])
        elif name in model.inputs:
            model.inputs[name].shape = [int(d) for d in dims.split(",")]

    loader = DataLoader(model)
    if args.input_data in ("random", "zero"):
        loader.generate_data(zero_input=args.input_data == "zero",
                             string_length=args.string_length,
                             string_data=args.string_data)
    elif os.path.isdir(args.input_data):
        loader.read_data_from_dir(args.input_data)
    else:
        loader.read_data_from_json(args.input_data)

    tpu_arena_url = args.tpu_arena_url
    if (args.shared_memory == "tpu" and not tpu_arena_url
            and args.service_kind == "triton"):
        # Arena pulls are endpoint-agnostic; the primary serves them.
        tpu_arena_url = endpoint_urls[0]
    data_manager = InferDataManager(
        model, loader, shared_memory=args.shared_memory,
        output_shm_size=args.output_shared_memory_size,
        tpu_arena_url=tpu_arena_url, batch_size=args.batch_size,
    )

    if model.response_cache_enabled:
        # Cache hits bypass queue/compute, so per-window server-stat
        # breakdowns under-report work (reference perf_analyzer prints
        # the same caveat when response_cache.enable is set).
        print("note: model has response caching enabled; server-side "
              "queue/compute breakdowns exclude cache hits",
              file=sys.stderr)
    elif model.composing_cache_enabled:
        # Composing-model cache hits short-circuit the ensemble
        # subgraph device-side (the dataflow path) and ARE visible in
        # tpu_ensemble_cache_hits_total — no breakdown caveat needed.
        print("note: a composing model has response caching enabled; "
              "cache hits short-circuit the ensemble subgraph (see "
              "tpu_ensemble_cache_hits_total)", file=sys.stderr)

    # -- server-side span tracing (--trace RATE) ----------------------
    trace_path = None
    trace_is_temp = False
    if args.trace and args.trace > 0:
        if args.service_kind not in ("triton", "inprocess"):
            print("warning: --trace requires --service-kind triton or "
                  "inprocess; ignoring", file=sys.stderr)
        else:
            if args.trace_file:
                trace_path = args.trace_file
            else:
                import tempfile

                fd, trace_path = tempfile.mkstemp(
                    prefix="client_tpu_trace_", suffix=".jsonl")
                os.close(fd)
                trace_is_temp = True
            try:
                # Global settings so composing/ensemble models trace
                # too; log_frequency=50 batches file writes off the
                # hot path (the OFF update after the run flushes the
                # tail), compact mode is what the harvest parses (set
                # trace_mode=chrome by hand for Perfetto).
                setup_backend.update_trace_settings("", {
                    "trace_level": "TIMESTAMPS",
                    "trace_rate": str(args.trace),
                    "trace_count": "-1",
                    "log_frequency": "50",
                    "trace_file": trace_path,
                    "trace_mode": "compact",
                })
            except InferenceServerException as e:
                print("warning: could not enable tracing (%s); "
                      "continuing without --trace" % e, file=sys.stderr)
                trace_path = None

    priority_mix = None
    if args.priority_mix:
        from client_tpu.perf.load_manager import parse_priority_mix

        try:
            priority_mix = parse_priority_mix(args.priority_mix)
        except ValueError as e:
            print("perf failed: bad --priority-mix: %s" % e,
                  file=sys.stderr)
            setup_backend.close()
            return 1
        if model.priority_levels:
            over = [level for level, _ in priority_mix
                    if level > model.priority_levels]
            if over:
                print("perf failed: --priority-mix levels %s exceed "
                      "the model's priority_levels %d"
                      % (over, model.priority_levels), file=sys.stderr)
                setup_backend.close()
                return 1
        else:
            print("note: model '%s' declares no priority_levels; the "
                  "server treats every class alike" % model.name,
                  file=sys.stderr)

    sequence_manager = None
    if (model.scheduler_type == SchedulerType.SEQUENCE
            or model.composing_sequential or args.sequence_id_range):
        start_id, id_range = 1, 2**31
        if args.sequence_id_range:
            parts = args.sequence_id_range.split(":")
            start_id = int(parts[0])
            if len(parts) > 1:
                id_range = int(parts[1]) - start_id
        sequence_manager = SequenceManager(
            start_id=start_id, id_range=id_range,
            sequence_length=args.sequence_length,
            sequence_length_variation=args.sequence_length_variation / 100.0,
        )

    config = MeasurementConfig(
        measurement_interval_ms=args.measurement_interval,
        measurement_mode=("count_windows" if args.request_count > 0
                          else args.measurement_mode),
        measurement_request_count=(args.request_count
                                   if args.request_count > 0
                                   else args.measurement_request_count),
        # --request-count measures exactly one fixed-count window; the
        # stability rule cannot apply to a single-trial run.
        max_trials=1 if args.request_count > 0 else args.max_trials,
        stability_threshold=args.stability_percentage / 100.0,
        latency_threshold_ms=args.latency_threshold,
        percentile=args.percentile,
        # REST/chat service kinds send one logical inference per
        # request regardless of -b (their payloads are not batched).
        batch_size=(args.batch_size
                    if args.service_kind in ("triton", "inprocess")
                    else 1),
    )

    manager_args = dict(
        factory=factory, model=model, data_loader=loader,
        data_manager=data_manager, async_mode=args.async_mode,
        streaming=args.streaming, max_threads=args.max_threads,
        sequence_manager=sequence_manager,
        priority_mix=priority_mix, tenant=args.tenant,
    )

    # -- staged overload burst (--overload) ---------------------------
    overload_scenario = None
    overload_backend = None
    if args.overload is not None:
        from client_tpu.server.chaos import OverloadScenario

        if args.service_kind not in ("triton", "inprocess"):
            print("perf failed: --overload requires --service-kind "
                  "triton or inprocess", file=sys.stderr)
            setup_backend.close()
            return 1
        # Request-shaping keys (priority/tenant) ride the same spec
        # but belong to the submitted requests, not the scenario.
        scenario_parts, burst_kwargs = [], {}
        for part in args.overload.split(","):
            key = part.partition("=")[0].strip()
            value = part.partition("=")[2].strip()
            if key == "priority":
                burst_kwargs["priority"] = int(value)
            elif key == "tenant":
                burst_kwargs["parameters"] = {"tenant": value}
            elif part.strip():
                scenario_parts.append(part)
        # raw: the burst must reach the server on every submit — a
        # retrying/breaker-guarded backend paces itself on Retry-After
        # (429 is retryable since this PR) or opens under sustained
        # rejects, and the saturation the flag exists to create never
        # holds; the scenario's submitted/rejected counts would also
        # hide rejects that a retry later converted to success.
        overload_backend = factory.create(raw=True)
        burst_inputs = data_manager.build_inputs(0, 0)
        burst_outputs = data_manager.build_outputs()

        def _burst_submit():
            overload_backend.infer(model.name, burst_inputs,
                                   outputs=burst_outputs, **burst_kwargs)

        overload_scenario = OverloadScenario(
            _burst_submit,
            **OverloadScenario.parse_spec(",".join(scenario_parts)))

    metrics_manager = None
    if args.collect_metrics:
        metrics_url = args.metrics_url
        if not metrics_url:
            from urllib.parse import urlsplit

            first_url = endpoint_urls[0]
            netloc = first_url if "://" in first_url else "//" + first_url
            host = urlsplit(netloc).hostname or "localhost"
            if ":" in host:  # bracket bare IPv6 for the URL
                host = "[%s]" % host
            metrics_url = "http://%s:8000/metrics" % host
        metrics_manager = MetricsManager(metrics_url, args.metrics_interval)
        try:
            metrics_manager.check_reachable()
        except Exception as e:
            print("warning: metrics endpoint %s unreachable (%s); "
                  "continuing without server metrics" % (metrics_url, e),
                  file=sys.stderr)
            metrics_manager = None

    if args.degrade_one is not None:
        from client_tpu.server.chaos import DegradeOneScenario

        scenario = DegradeOneScenario(
            scopes=[m[0] for m in fleet_members],
            kill_fns=[m[3] for m in fleet_members],
            **DegradeOneScenario.parse_spec(args.degrade_one),
        ).start()
    if overload_scenario is not None:
        overload_scenario.start()

    mode = "concurrency"
    try:
        if args.request_rate_range:
            mode = "request_rate"
            start, end, step = _parse_range(args.request_rate_range, float)
            manager = RequestRateManager(
                distribution=args.request_distribution, **manager_args
            )
            manager.init()
            profiler = InferenceProfiler(
                manager, config, setup_backend, model.name, args.verbose,
                metrics_manager=metrics_manager,
                composing_models=model.composing_models)
            results = profiler.profile_request_rate_range(start, end, step)
        elif args.request_intervals:
            mode = "request_rate"
            manager = CustomLoadManager(
                request_intervals_file=args.request_intervals,
                **manager_args)
            manager.init()
            profiler = InferenceProfiler(
                manager, config, setup_backend, model.name, args.verbose,
                metrics_manager=metrics_manager,
                composing_models=model.composing_models)
            results = profiler.profile_custom_intervals()
        elif args.periodic_concurrency_range:
            start, end, step = _parse_range(args.periodic_concurrency_range)
            manager = PeriodicConcurrencyManager(
                concurrency_start=start, concurrency_end=end,
                concurrency_step=step, request_period=args.request_period,
                **manager_args,
            )
            manager.init()
            profiler = InferenceProfiler(
                manager, config, setup_backend, model.name, args.verbose,
                metrics_manager=metrics_manager,
                composing_models=model.composing_models)
            manager.run_ramp()
            results = [profiler.profile_single_level()]
            manager.stop()
        else:
            start, end, step = _parse_range(args.concurrency_range or "1")
            manager = ConcurrencyManager(**manager_args)
            manager.init()
            profiler = InferenceProfiler(
                manager, config, setup_backend, model.name, args.verbose,
                metrics_manager=metrics_manager,
                composing_models=model.composing_models)
            results = profiler.profile_concurrency_range(start, end, step)
    except (InferenceServerException, ValueError, OSError) as e:
        print("perf failed: %s" % e, file=sys.stderr)
        return 1
    finally:
        if metrics_manager is not None:
            metrics_manager.stop()
            if metrics_manager.scrape_failures:
                print("warning: %d metrics scrapes failed during the run"
                      % metrics_manager.scrape_failures, file=sys.stderr)
        try:
            manager.cleanup()
        except Exception:
            pass
        if trace_path is not None:
            # Turning tracing off also flushes any buffered records
            # under the run's settings, so the harvest sees the tail.
            try:
                setup_backend.update_trace_settings(
                    "", {"trace_level": "OFF"})
            except Exception:
                pass
        setup_backend.close()
        if scenario is not None:
            scenario.stop()
        if overload_scenario is not None:
            overload_scenario.stop()
        if overload_backend is not None:
            try:
                overload_backend.close()
            except Exception:
                pass
        if endpoint_pool is not None:
            endpoint_pool.close()
        for _scope, _server, _core, stop_fn in fleet_members:
            try:
                stop_fn()
            except Exception:  # already killed by the scenario
                pass

    print_report(results, args.percentile, mode)
    if priority_mix is not None or args.tenant or overload_scenario:
        from client_tpu.perf.report import print_qos_report

        description_parts = []
        if priority_mix is not None:
            description_parts.append("mix %s" % args.priority_mix)
        if args.tenant:
            description_parts.append("tenant %s" % args.tenant)
        if overload_scenario is not None:
            burst = overload_scenario.stats()
            description_parts.append(
                "overload burst: %d submitted, %d rejected"
                % (burst["submitted"], burst["rejected"]))
        print_qos_report(results, ", ".join(description_parts))
    if trace_path is not None:
        from client_tpu.perf.report import print_trace_report

        print_trace_report(trace_path)
        if trace_is_temp:
            try:
                os.unlink(trace_path)
            except OSError:
                pass
    if endpoint_pool is not None:
        from client_tpu.perf.report import print_failover_report

        description = "%d endpoints" % len(endpoint_urls)
        if scenario is not None:
            events = []
            if scenario.spiked.is_set():
                events.append("latency spike")
            if scenario.killed.is_set():
                events.append("killed")
            if events:
                description += ", one %s" % " then ".join(events)
        print_failover_report(results, robust.fleet_totals(),
                              endpoint_pool.stats(), description)
    if args.chaos or retries > 0:
        from client_tpu.perf.report import print_chaos_report

        injected = None
        if args.chaos and args.service_kind == "inprocess":
            from client_tpu.server import chaos as chaos_mod

            injected = chaos_mod.stats()
            chaos_mod.configure(None)  # leave the process clean
        print_chaos_report(results, robust.retry_total(), injected,
                           chaos_config.describe() if chaos_config
                           else "no injection",
                           unrecovered=robust.exhausted_total())
    slo_ok = True
    if args.slo:
        from client_tpu.perf.report import print_slo_report

        # Compliance reads one final scrape: inprocess renders the
        # core's exposition directly, remote runs reuse the metrics
        # manager's URL (the burn-rate windows live server-side, so a
        # single post-run scrape carries the whole verdict).
        slo_metrics = None
        if args.service_kind == "inprocess" and core is not None:
            from client_tpu.perf.metrics_manager import parse_prometheus

            slo_metrics = parse_prometheus(core.metrics_text())
        elif metrics_manager is not None:
            try:
                slo_metrics = metrics_manager.scrape_once()
            except Exception as e:  # noqa: BLE001 — degraded scrape
                print("warning: --slo final scrape failed: %s" % e,
                      file=sys.stderr)
        if slo_metrics is None:
            print("perf --slo: no metrics source (use --service-kind "
                  "inprocess, or --collect-metrics with a reachable "
                  "--metrics-url); treating as a violation",
                  file=sys.stderr)
            slo_ok = False
        else:
            slo_ok = print_slo_report(slo_metrics,
                                      strict=args.slo_strict)
    if args.latency_report_file:
        write_csv(args.latency_report_file, results, mode)
    if args.profile_export_file:
        export_profile(args.profile_export_file, results, model.name,
                       args.service_kind, args.url, mode)
    return 0 if slo_ok else 1


def main():
    sys.exit(run())


if __name__ == "__main__":
    main()
