"""Model metadata/config normalization for the perf harness (parity:
model_parser.h:41-76 — ModelTensor, scheduler type, decoupled flag)."""

from __future__ import annotations

import enum
from typing import Dict, List, Optional

from client_tpu.utils import InferenceServerException


class SchedulerType(enum.Enum):
    NONE = "none"
    DYNAMIC = "dynamic"
    SEQUENCE = "sequence"
    ENSEMBLE = "ensemble"
    # Ensemble whose composing chain contains a sequence-batched model
    # (reference model_parser.h:63) — sequence semantics apply.
    ENSEMBLE_SEQUENCE = "ensemble_sequence"


class ModelTensor:
    def __init__(self, name: str, datatype: str, shape: List[int],
                 optional: bool = False, is_shape_tensor: bool = False):
        self.name = name
        self.datatype = datatype
        self.shape = shape
        self.optional = optional
        self.is_shape_tensor = is_shape_tensor


class ParsedModel:
    def __init__(self):
        self.name = ""
        self.version = ""
        self.platform = ""
        self.max_batch_size = 0
        self.inputs: Dict[str, ModelTensor] = {}
        self.outputs: Dict[str, ModelTensor] = {}
        self.scheduler_type = SchedulerType.NONE
        self.decoupled = False
        # sequence_batching details (populated for SEQUENCE models):
        # the same knobs the server's scheduler enforces, so the load
        # manager and report can size/describe sequence runs.
        self.sequence_strategy = "direct"
        self.max_candidate_sequences = 0
        self.max_sequence_idle_us = 0
        self.sequence_controls: List[Dict] = []
        self.sequence_states: List[Dict] = []
        self.sequence_preferred_batch_sizes: List[int] = []
        self.composing_models: List[str] = []
        # True when any composing model is sequence-batched: the load
        # manager must then drive sequences even though the top model
        # is an ensemble (reference GetComposingSchedulerType).
        self.composing_sequential = False
        self.response_cache_enabled = False
        # Multi-tenant QoS knobs (dynamic_batching.priority_levels
        # schema): the harness uses them to validate a --priority-mix
        # against the served config and to describe the run.
        self.priority_levels = 0
        self.default_priority_level = 0
        self.shed_watermark = 0.0
        # True when any composing model of an ensemble enables the
        # response cache: the cache-latency caveat applies even though
        # the TOP model's config carries no response_cache section
        # (its composing steps' breakdowns exclude their cache hits).
        self.composing_cache_enabled = False
        # Replica serving (instance_group): total declared replicas
        # across the model's instance groups (0 = single fault
        # domain), so reports can annotate per-replica expectations.
        self.instance_group_count = 0
        # Mesh-slice serving (instance_group.shard_mesh): the shard
        # axes each replica is sharded over ([] = one-device replicas)
        # and the devices per slice (axis-size product, 1 = unsharded)
        # — so reports can annotate per-slice device budgets.
        self.shard_mesh_axes: List = []
        self.slice_width = 1


class ModelParser:
    """Builds a ParsedModel from backend metadata+config dicts."""

    def parse(self, backend, model_name: str, model_version: str = "",
              batch_size: int = 1,
              bls_composing_models: Optional[List[str]] = None
              ) -> ParsedModel:
        metadata = backend.model_metadata(model_name, model_version)
        config = backend.model_config(model_name, model_version)
        model = ParsedModel()
        model.name = metadata.get("name", model_name)
        versions = metadata.get("versions", [])
        model.version = model_version or (versions[-1] if versions else "")
        model.platform = metadata.get("platform", "")
        model.max_batch_size = int(config.get("max_batch_size", 0))
        if batch_size > 1 and model.max_batch_size == 0:
            raise InferenceServerException(
                "batch size %d requested but model '%s' does not support "
                "batching" % (batch_size, model_name)
            )
        if batch_size > model.max_batch_size > 0:
            raise InferenceServerException(
                "batch size %d exceeds model max_batch_size %d"
                % (batch_size, model.max_batch_size)
            )

        config_inputs = {t.get("name"): t for t in config.get("input", [])}
        for tensor in metadata.get("inputs", []):
            shape = [int(d) for d in tensor.get("shape", [])]
            if model.max_batch_size > 0 and shape and shape[0] == -1:
                shape = shape[1:]  # strip batch dim
            extra = config_inputs.get(tensor["name"], {})
            model.inputs[tensor["name"]] = ModelTensor(
                tensor["name"], tensor.get("datatype", ""), shape,
                optional=bool(extra.get("optional", False)),
                is_shape_tensor=bool(extra.get("is_shape_tensor", False)),
            )
        for tensor in metadata.get("outputs", []):
            shape = [int(d) for d in tensor.get("shape", [])]
            if model.max_batch_size > 0 and shape and shape[0] == -1:
                shape = shape[1:]
            model.outputs[tensor["name"]] = ModelTensor(
                tensor["name"], tensor.get("datatype", ""), shape
            )

        if "ensemble_scheduling" in config:
            model.scheduler_type = SchedulerType.ENSEMBLE
        elif "sequence_batching" in config:
            model.scheduler_type = SchedulerType.SEQUENCE
            self._parse_sequence_batching(
                config["sequence_batching"] or {}, model)
        elif "dynamic_batching" in config:
            model.scheduler_type = SchedulerType.DYNAMIC
        batching = config.get("dynamic_batching") or {}
        # proto-JSON stringifies u64 — numeric fields go through int().
        model.priority_levels = int(
            batching.get("priority_levels", 0) or 0)
        model.default_priority_level = int(
            batching.get("default_priority_level", 0) or 0)
        model.shed_watermark = float(
            batching.get("shed_watermark", 0.0) or 0.0)
        policy = config.get("model_transaction_policy", {})
        model.decoupled = bool(policy.get("decoupled", False))
        cache = config.get("response_cache", {})
        model.response_cache_enabled = bool(cache.get("enable", False))
        model.instance_group_count = sum(
            int(group.get("count", 0) or 0)
            for group in config.get("instance_group", []) or [])
        for group in config.get("instance_group", []) or []:
            shard_mesh = group.get("shard_mesh", {}) or {}
            names = shard_mesh.get("axis_names", []) or []
            sizes = shard_mesh.get("axis_sizes", []) or []
            axes = [(str(axis), int(size))
                    for axis, size in zip(names, sizes) if int(size) > 1]
            if axes:
                model.shard_mesh_axes = axes
                model.slice_width = 1
                for _axis, size in axes:
                    model.slice_width *= size
                break  # one shard spec per model, first group wins

        # Composing models: ensemble steps (recursively — an ensemble
        # step may itself be an ensemble) plus any BLS children named
        # explicitly (a BLS pipeline's callees are invisible in the
        # config, reference --bls-composing-models). Pairing their
        # per-window stats with the top model's is what makes
        # ensemble profiles add up.
        seen = set()
        self._add_composing(backend, config, model, seen)
        for name in bls_composing_models or []:
            self._add_child(backend, name, model, seen)
        if (model.scheduler_type is SchedulerType.ENSEMBLE
                and model.composing_sequential):
            model.scheduler_type = SchedulerType.ENSEMBLE_SEQUENCE
        return model

    @staticmethod
    def _parse_sequence_batching(section: dict, model: ParsedModel) -> None:
        """Full sequence_batching parse (strategy, controls, state,
        idle timeout) so the harness sees the same config the server's
        scheduler enforces. proto-JSON stringifies (u)int64 — numeric
        fields go through int()."""
        model.sequence_strategy = str(
            section.get("strategy") or "direct").lower()
        model.max_candidate_sequences = int(
            section.get("max_candidate_sequences", 0) or 0)
        model.max_sequence_idle_us = int(
            section.get("max_sequence_idle_microseconds", 0) or 0)
        model.sequence_controls = [
            {"name": c.get("name", ""), "kind": c.get("kind", ""),
             "datatype": str(c.get("data_type", "")).replace("TYPE_", "")}
            for c in section.get("control_input", [])
        ]
        model.sequence_states = [
            {"input_name": s.get("input_name", ""),
             "output_name": s.get("output_name", ""),
             "datatype": str(s.get("data_type", "")).replace("TYPE_", ""),
             "dims": [int(d) for d in s.get("dims", [])]}
            for s in section.get("state", [])
        ]
        model.sequence_preferred_batch_sizes = [
            int(size) for size in section.get("preferred_batch_size", [])
        ]

    def _add_composing(self, backend, config: dict, model: ParsedModel,
                       seen: set) -> None:
        for step in config.get("ensemble_scheduling", {}).get("step", []):
            name = step.get("model_name", "")
            if name:
                self._add_child(backend, name, model, seen)

    def _add_child(self, backend, name: str, model: ParsedModel,
                   seen: set) -> None:
        if name in seen:
            return
        seen.add(name)
        model.composing_models.append(name)
        try:
            child_config = backend.model_config(name)
        except InferenceServerException:
            return  # unavailable child: keep the name for stat pairing
        if "sequence_batching" in child_config:
            model.composing_sequential = True
        if (child_config.get("response_cache") or {}).get("enable"):
            model.composing_cache_enabled = True
        self._add_composing(backend, child_config, model, seen)
