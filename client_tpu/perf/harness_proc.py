"""Run the native perf_analyzer as a subprocess and parse its CSV.

jax-free on purpose: both the bench child (which owns the device) and
the bench orchestrator (which must never import jax — bench.py's
whole design is that device work lives in killable children) drive
the C++ harness through this one helper, so the command assembly,
warm-pass semantics, and CSV parse cannot drift apart.
"""

from __future__ import annotations

import pathlib
import subprocess


def run_native(binary: pathlib.Path, address: str, model: str, batch: int,
               concurrency: int, shared_memory: str, output_shm: int,
               timeout: float, warm: bool = False, streaming: bool = False,
               input_data: str | None = None, window_ms: int = 2000,
               trials: int = 4, stability: int = 20,
               protocol: str = "") -> tuple[float, float]:
    """One stable measurement via the C++ harness; (throughput, p50_us).
    ``warm=True`` runs a single short unmeasured pass first so one-time
    XLA utility-kernel compiles (batch fusion, output slicing) land
    outside the counted window."""
    csv = "/tmp/bench_%s_latency.csv" % model
    cmd = [str(binary), "-m", model, "-u", address,
           "-b", str(batch),
           "--concurrency-range", str(concurrency),
           "--async",
           "-p", "1500" if warm else str(window_ms),
           "-r", "1" if warm else str(trials),
           "-s", "99" if warm else str(stability),
           "--max-threads", "8",
           "-f", csv]
    if warm:
        # Hold the warm window open until the first requests actually
        # complete (first-call XLA compiles can outlast any fixed
        # window, and an all-empty window is a harness error).
        cmd += ["--measurement-mode", "count_windows",
                "--measurement-request-count", str(max(2, concurrency))]
    if protocol:
        cmd += ["-i", protocol]
    if streaming:
        cmd.append("--streaming")
    if input_data is not None:
        cmd += ["--input-data", input_data]
    if shared_memory != "none":
        cmd += ["--shared-memory", shared_memory,
                "--output-shared-memory-size", str(output_shm)]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError("perf_analyzer rc=%d: %s"
                           % (proc.returncode, proc.stderr[-500:]))
    with open(csv) as f:
        f.readline()  # header
        row = f.readline().strip().split(",")
    if len(row) < 3:
        # A header-only CSV (analyzer exited 0 with nothing measured)
        # must not take the whole bench down with an IndexError.
        raise RuntimeError("perf_analyzer wrote no result row")
    return float(row[1]), float(row[2])
