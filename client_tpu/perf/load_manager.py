"""Load generation: InferContext slots, shared-memory data managers,
sequence bookkeeping, and the load-manager hierarchy
(concurrency / request-rate / custom-interval / periodic-concurrency),
mirroring the reference's perf_analyzer core (load_manager.h:48,
concurrency_manager.h:95, request_rate_manager.h:57,
infer_data_manager_shm.h:93, sequence_manager.h:46).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu.perf.client_backend import BackendKind, ClientBackendFactory
from client_tpu.perf.data_loader import DataLoader
from client_tpu.perf.model_parser import ParsedModel, SchedulerType
from client_tpu.utils import InferenceServerException

NANOS = 1_000_000_000


class RequestRecord:
    """Timestamps for one request and its response(s) (parity:
    request_record.h:63). ``priority``/``tenant`` label the record's
    QoS class so the report can break latency and goodput down per
    class (0/None = unclassed)."""

    __slots__ = ("start_ns", "end_ns", "delayed", "sequence_end", "error",
                 "priority", "tenant")

    def __init__(self, start_ns: int, delayed: bool = False,
                 sequence_end: bool = True, priority: int = 0,
                 tenant: Optional[str] = None):
        self.start_ns = start_ns
        self.end_ns: List[int] = []
        self.delayed = delayed
        self.sequence_end = sequence_end
        self.error: Optional[Exception] = None
        self.priority = priority
        self.tenant = tenant

    @property
    def valid(self) -> bool:
        return bool(self.end_ns) and self.error is None

    @property
    def latency_ns(self) -> int:
        return self.end_ns[-1] - self.start_ns


class ThreadStat:
    """Per-worker request records + health (parity: ThreadStat in
    load_manager.h:137)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.records: List[RequestRecord] = []
        self.status: Optional[Exception] = None
        self.idle_ns = 0

    def add_record(self, record: RequestRecord):
        with self.lock:
            self.records.append(record)


# -- shared-memory kinds ---------------------------------------------------

SHM_NONE = "none"
SHM_SYSTEM = "system"
SHM_TPU = "tpu"


class InferDataManager:
    """Prepares the InferInput/InferRequestedOutput objects each
    context sends. In shm modes it creates+populates+registers one
    region per input x stream x step named `<input>_<stream>_<step>`
    and routes inputs through set_shared_memory (parity:
    infer_data_manager_shm.h:93-136)."""

    def __init__(self, model: ParsedModel, data_loader: DataLoader,
                 shared_memory: str = SHM_NONE,
                 output_shm_size: int = 102400,
                 tpu_arena_url: str = "", batch_size: int = 1):
        self._model = model
        self._loader = data_loader
        self._shm = shared_memory
        self._output_shm_size = output_shm_size
        self._tpu_arena_url = tpu_arena_url
        self._batch = batch_size
        self._system_handles: list = []
        self._tpu_handles: list = []
        self._registered = []
        self._output_regions: Dict[str, str] = {}

    def init(self, backend) -> None:
        if self._shm == SHM_NONE:
            return
        if self._shm == SHM_TPU:
            import client_tpu.utils.tpu_shared_memory as tpushm

            if self._tpu_arena_url:
                tpushm.set_arena_endpoint(self._tpu_arena_url)
        for stream in range(self._loader.stream_count):
            for step in range(self._loader.step_count(stream)):
                for name, tensor in self._model.inputs.items():
                    data = self._loader.get_input_data(name, stream, step)
                    region = "%s_%d_%d" % (name, stream, step)
                    self._create_region(
                        backend, region, data.raw_bytes(), data.array,
                        data.datatype, copies=self._copies_for(tensor),
                        batchable=self._batchable(tensor))
        # One region per output name, shared by all in-flight requests
        # (reference behavior). Outputs are never validated by the
        # harness; concurrent placements interleave harmlessly — the
        # arena stores whole-array references under a lock and system
        # regions take overlapping memcpys without faulting.
        for name in self._model.outputs:
            region = "out_%s" % name
            self._create_output_region(backend, region)
            self._output_regions[name] = region

    def _batchable(self, tensor) -> bool:
        """One rule for both shape batching and data replication:
        ordinary inputs of batching models batch; shape tensors never
        do (their values describe shapes — one value set per batch,
        reference ModelTensor.is_shape_tensor)."""
        return self._model.max_batch_size > 0 and not tensor.is_shape_tensor

    def _copies_for(self, tensor) -> int:
        return max(self._batch, 1) if self._batchable(tensor) else 1

    def _create_region(self, backend, region, raw, array, datatype,
                       copies=1, batchable=False):
        byte_size = max(len(raw) * copies, 1)
        if self._shm == SHM_SYSTEM:
            import client_tpu.utils.shared_memory as shm

            handle = shm.create_shared_memory_region(
                region, "/perf_" + region, byte_size
            )
            shm.set_shared_memory_region(handle, [array] * copies)
            backend.register_system_shared_memory(region, "/perf_" + region,
                                                  byte_size)
            self._system_handles.append(handle)
        else:
            import client_tpu.utils.tpu_shared_memory as tpushm

            handle = tpushm.create_shared_memory_region(region, byte_size, 0)
            if batchable:
                # Store with the leading batch dim EVEN at batch 1: the
                # arena's zero-copy fast path requires the stored shape
                # to equal the request's declared shape (build_inputs
                # declares [batch, ...] for batchable tensors).
                tpushm.set_shared_memory_region(
                    handle, [np.stack([array] * copies)])
            else:
                tpushm.set_shared_memory_region(handle, [array])
            backend.register_tpu_shared_memory(
                region, tpushm.get_raw_handle(handle), 0, byte_size
            )
            self._tpu_handles.append(handle)
        self._registered.append(region)

    def _create_output_region(self, backend, region):
        byte_size = self._output_shm_size
        if self._shm == SHM_SYSTEM:
            import client_tpu.utils.shared_memory as shm

            handle = shm.create_shared_memory_region(
                region, "/perf_" + region, byte_size
            )
            backend.register_system_shared_memory(region, "/perf_" + region,
                                                  byte_size)
            self._system_handles.append(handle)
        else:
            import client_tpu.utils.tpu_shared_memory as tpushm

            handle = tpushm.create_shared_memory_region(region, byte_size, 0)
            backend.register_tpu_shared_memory(
                region, tpushm.get_raw_handle(handle), 0, byte_size
            )
            self._tpu_handles.append(handle)
        self._registered.append(region)

    def build_inputs(self, stream: int = 0, step: int = 0) -> List[InferInput]:
        inputs = []
        for name, tensor in self._model.inputs.items():
            data = self._loader.get_input_data(name, stream, step)
            copies = self._copies_for(tensor)
            batchable = self._batchable(tensor)
            shape = data.shape
            if batchable and self._batch >= 1:
                shape = [self._batch] + shape
            infer_input = InferInput(name, shape, tensor.datatype)
            if self._shm == SHM_NONE:
                if copies > 1:
                    infer_input.set_data_from_numpy(
                        np.stack([data.array] * copies))
                elif batchable:
                    infer_input.set_data_from_numpy(data.array[None])
                else:
                    infer_input.set_data_from_numpy(data.array)
            else:
                region = "%s_%d_%d" % (name, stream, step)
                raw_len = len(data.raw_bytes()) * copies
                infer_input.set_shared_memory(region, raw_len)
            inputs.append(infer_input)
        return inputs

    def build_outputs(self) -> Optional[List[InferRequestedOutput]]:
        if self._shm == SHM_NONE:
            return None
        outputs = []
        for name in self._model.outputs:
            requested = InferRequestedOutput(name)
            requested.set_shared_memory(self._output_regions[name],
                                        self._output_shm_size)
            outputs.append(requested)
        return outputs

    def cleanup(self, backend) -> None:
        try:
            if self._shm == SHM_SYSTEM:
                backend.unregister_system_shared_memory("")
            elif self._shm == SHM_TPU:
                backend.unregister_tpu_shared_memory("")
        except Exception:
            pass
        import client_tpu.utils.shared_memory as shm

        for handle in self._system_handles:
            try:
                shm.destroy_shared_memory_region(handle)
            except Exception:
                pass
        if self._tpu_handles:
            import client_tpu.utils.tpu_shared_memory as tpushm

            for handle in self._tpu_handles:
                try:
                    tpushm.destroy_shared_memory_region(handle)
                except Exception:
                    pass
        self._system_handles = []
        self._tpu_handles = []


class SequenceManager:
    """Sequence-id allocation and per-sequence progress (parity:
    sequence_manager.h:46-150)."""

    def __init__(self, start_id: int = 1, id_range: int = 2**31,
                 sequence_length: int = 20,
                 sequence_length_variation: float = 0.2, seed: int = 3):
        self._next_id = start_id
        self._start = start_id
        self._range = id_range
        self._length = sequence_length
        self._variation = sequence_length_variation
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._active: Dict[int, dict] = {}

    def new_sequence(self, data_stream_count: int = 1) -> dict:
        with self._lock:
            seq_id = self._start + (self._next_id - self._start) % self._range
            self._next_id += 1
            remaining = max(
                1,
                int(self._length
                    * (1 + self._rng.uniform(-self._variation,
                                             self._variation))),
            )
            state = {
                "id": seq_id,
                "remaining": remaining,
                "step": 0,
                "stream": self._rng.randrange(data_stream_count),
            }
            self._active[seq_id] = state
            return state

    def advance(self, state: dict) -> dict:
        """Returns kwargs for the next request in this sequence and
        updates progress."""
        with self._lock:
            start = state["step"] == 0
            state["remaining"] -= 1
            end = state["remaining"] <= 0
            kwargs = {
                "sequence_id": state["id"],
                "sequence_start": start,
                "sequence_end": end,
            }
            state["step"] += 1
            if end:
                self._active.pop(state["id"], None)
            return kwargs


# -- ctx id trackers (parity: ctx_id_tracker_factory.h) -------------------


class FifoCtxIdTracker:
    def __init__(self):
        self._free: List[int] = []
        self._cv = threading.Condition()

    def reset(self, count: int):
        with self._cv:
            self._free = list(range(count))
            self._cv.notify_all()

    def available(self) -> bool:
        with self._cv:
            return bool(self._free)

    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        with self._cv:
            if not self._free and not self._cv.wait_for(
                lambda: bool(self._free), timeout=timeout
            ):
                return None
            return self._free.pop(0)

    def release(self, ctx_id: int):
        with self._cv:
            self._free.append(ctx_id)
            self._cv.notify()


class RandCtxIdTracker(FifoCtxIdTracker):
    def get(self, timeout: Optional[float] = None) -> Optional[int]:
        with self._cv:
            if not self._free and not self._cv.wait_for(
                lambda: bool(self._free), timeout=timeout
            ):
                return None
            idx = random.randrange(len(self._free))
            return self._free.pop(idx)


# -- load managers ---------------------------------------------------------


def build_priority_schedule(mix: List,
                            slots: Optional[int] = None) -> List[int]:
    """Deterministic interleaved class schedule from (level, weight)
    pairs — smooth weighted round-robin, so a 1:4 mix issues
    2,2,1,2,2 rather than 1,2,2,2,2 blocks (blocked assignment would
    make the high class's latency depend on its slot phase). The
    schedule is sized so even the smallest-weight class gets at least
    one slot (a '1:0.01,2:0.99' mix must still issue priority-1
    requests), capped at 1000 slots — a rarer class than 1/1000 gets
    rounded up to that share."""
    mix = [(int(level), float(weight)) for level, weight in mix
           if weight > 0]
    if not mix:
        return [0]
    total = sum(weight for _, weight in mix)
    if slots is None:
        import math

        smallest = min(weight for _, weight in mix)
        slots = min(max(20, math.ceil(total / smallest)), 1000)
    current = {level: 0.0 for level, _ in mix}
    schedule: List[int] = []
    for _ in range(slots):
        for level, weight in mix:
            current[level] += weight
        best = max(mix, key=lambda lw: current[lw[0]])[0]
        current[best] -= total
        schedule.append(best)
    # Rounding starved ultra-rare classes entirely (slots is capped at
    # 1000): append one slot per starved class rather than silently
    # dropping it — writing them all into one shared tail slot would
    # leave every starved class but the last unissued, and overwriting
    # existing slots could erase another class's only slot.
    schedule.extend(level for level, _ in mix if level not in schedule)
    return schedule


def parse_priority_mix(spec: str) -> List:
    """``"1:0.2,2:0.8"`` (level:weight pairs) -> [(1, 0.2), (2, 0.8)];
    a bare ``"1,2"`` means equal weights."""
    mix = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        level, sep, weight = part.partition(":")
        level = int(level)
        if level < 1:
            # 0 would issue unclassed requests (the server substitutes
            # its default level) while the report claims a mix was
            # applied; negatives are rejected INVALID_ARGUMENT at the
            # server mid-run. Fail fast at parse time instead.
            raise ValueError(
                "priority level %d out of range (levels start at 1)"
                % level)
        weight = float(weight) if sep else 1.0
        if weight <= 0:
            raise ValueError(
                "priority level %d has non-positive weight %g"
                % (level, weight))
        mix.append((level, weight))
    if not mix:
        raise ValueError("empty --priority-mix spec")
    return mix


class LoadManager:
    """Base: owns backends, data manager, worker threads, records."""

    def __init__(
        self,
        factory: ClientBackendFactory,
        model: ParsedModel,
        data_loader: DataLoader,
        data_manager: InferDataManager,
        async_mode: bool = True,
        streaming: bool = False,
        max_threads: int = 16,
        sequence_manager: Optional[SequenceManager] = None,
        priority_mix: Optional[List] = None,
        tenant: Optional[str] = None,
    ):
        self._factory = factory
        self._model = model
        self._loader = data_loader
        self._data_manager = data_manager
        self._async = async_mode
        self._streaming = streaming
        self._max_threads = max_threads
        self._sequence_manager = sequence_manager
        self._threads: List[threading.Thread] = []
        self._thread_stats: List[ThreadStat] = []
        self._stop = threading.Event()
        self._setup_backend = None
        self._step_cursor: Dict[int, int] = {}
        self._step_lock = threading.Lock()
        # QoS labeling: every issued request draws its priority class
        # from a deterministic interleaved schedule (--priority-mix)
        # and carries the run's tenant identity (--tenant) as the
        # `tenant` parameter.
        self._tenant = tenant
        self._priority_schedule = (
            build_priority_schedule(priority_mix) if priority_mix
            else None)
        self._qos_cursor = 0
        self._qos_lock = threading.Lock()

    def _qos_assign(self) -> tuple:
        """(priority, tenant) for the next issued request."""
        priority = 0
        if self._priority_schedule is not None:
            with self._qos_lock:
                priority = self._priority_schedule[
                    self._qos_cursor % len(self._priority_schedule)]
                self._qos_cursor += 1
        return priority, self._tenant

    @staticmethod
    def _qos_kwargs(priority: int, tenant: Optional[str]) -> dict:
        kwargs: dict = {}
        if priority:
            kwargs["priority"] = priority
        if tenant:
            kwargs["parameters"] = {"tenant": tenant}
        return kwargs

    # setup ---------------------------------------------------------------
    def init(self) -> None:
        self._setup_backend = self._factory.create()
        self._data_manager.init(self._setup_backend)

    def cleanup(self) -> None:
        self.stop()
        if self._setup_backend is not None:
            self._data_manager.cleanup(self._setup_backend)
            self._setup_backend.close()
            self._setup_backend = None

    def _next_step(self, stream: int = 0) -> int:
        with self._step_lock:
            steps = max(self._loader.step_count(stream), 1)
            step = self._step_cursor.get(stream, 0)
            self._step_cursor[stream] = (step + 1) % steps
            return step

    def _sequence_step(self, holder: dict):
        """Advance the sequence owned by a context slot; a slot runs
        one sequence to completion before starting the next (the
        reference's per-context sequence semantics,
        infer_context.h:111). Returns (request kwargs, data stream,
        data step) — sequences replay their own stream's steps in
        order."""
        if self._sequence_manager is None:
            return {}, 0, None
        state = holder.get("state")
        if state is None:
            state = self._sequence_manager.new_sequence(
                self._loader.stream_count
            )
            holder["state"] = state
        stream = state["stream"]
        step = state["step"] % max(self._loader.step_count(stream), 1)
        kwargs = self._sequence_manager.advance(state)
        if kwargs["sequence_end"]:
            holder["state"] = None
        return kwargs, stream, step

    # record access -------------------------------------------------------
    def swap_request_records(self) -> List[RequestRecord]:
        """Drain all worker records (parity: SwapRequestRecords)."""
        records: List[RequestRecord] = []
        for stat in self._thread_stats:
            with stat.lock:
                records.extend(stat.records)
                stat.records = []
        return records

    def count_collected_requests(self) -> int:
        return sum(len(s.records) for s in self._thread_stats)

    def check_health(self) -> None:
        for stat in self._thread_stats:
            if stat.status is not None:
                raise InferenceServerException(
                    "worker thread failed: %s" % stat.status
                )

    def stop(self) -> None:
        self._stop.set()
        for thread in self._threads:
            thread.join(timeout=30)
        self._threads = []
        self._stop.clear()


class ConcurrencyManager(LoadManager):
    """Maintains exactly N in-flight requests (parity:
    concurrency_manager.h:95 + concurrency_worker.cc:42-175)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._concurrency = 0

    def change_concurrency_level(self, concurrency: int) -> None:
        self.stop()
        self._concurrency = concurrency
        if concurrency == 0:
            return
        n_threads = min(concurrency, self._max_threads)
        base, extra = divmod(concurrency, n_threads)
        self._thread_stats = [ThreadStat() for _ in range(n_threads)]
        self._threads = []
        for i in range(n_threads):
            ctxs = base + (1 if i < extra else 0)
            thread = threading.Thread(
                target=self._worker, args=(self._thread_stats[i], ctxs),
                daemon=True,
            )
            self._threads.append(thread)
            thread.start()

    def _worker(self, stat: ThreadStat, n_ctx: int) -> None:
        try:
            backend = self._factory.create()
        except Exception as e:
            stat.status = e
            return
        try:
            if self._streaming:
                self._stream_worker(stat, backend, n_ctx)
            elif self._async:
                self._async_worker(stat, backend, n_ctx)
            else:
                self._sync_worker(stat, backend, n_ctx)
        except Exception as e:
            stat.status = e
        finally:
            try:
                backend.close()
            except Exception:
                pass

    def _make_request(self, holder: dict):
        kwargs, stream, seq_step = self._sequence_step(holder)
        step = seq_step if seq_step is not None else self._next_step(stream)
        inputs = self._data_manager.build_inputs(stream, step)
        outputs = self._data_manager.build_outputs()
        priority, tenant = self._qos_assign()
        kwargs.update(self._qos_kwargs(priority, tenant))
        return inputs, outputs, kwargs, priority, tenant

    def _sync_worker(self, stat, backend, n_ctx):
        holder: dict = {}
        while not self._stop.is_set():
            inputs, outputs, kwargs, priority, tenant = \
                self._make_request(holder)
            record = RequestRecord(time.monotonic_ns(),
                                   priority=priority, tenant=tenant)
            try:
                backend.infer(self._model.name, inputs, outputs=outputs,
                              **kwargs)
                record.end_ns.append(time.monotonic_ns())
            except InferenceServerException as e:
                record.error = e
            stat.add_record(record)

    def _async_worker(self, stat, backend, n_ctx):
        tracker = FifoCtxIdTracker()
        tracker.reset(n_ctx)
        holders = [dict() for _ in range(n_ctx)]

        def _done(record, ctx_id):
            def callback(result, error):
                record.end_ns.append(time.monotonic_ns())
                if error is not None:
                    record.error = error
                stat.add_record(record)
                tracker.release(ctx_id)

            return callback

        while not self._stop.is_set():
            ctx_id = tracker.get(timeout=0.1)
            if ctx_id is None:
                continue
            if self._stop.is_set():
                tracker.release(ctx_id)
                break
            inputs, outputs, kwargs, priority, tenant = \
                self._make_request(holders[ctx_id])
            record = RequestRecord(time.monotonic_ns(),
                                   priority=priority, tenant=tenant)
            try:
                backend.async_infer(_done(record, ctx_id), self._model.name,
                                    inputs, outputs=outputs, **kwargs)
            except InferenceServerException as e:
                # Submission itself was shed (e.g. every endpoint in
                # the pool ejected): that is ONE failed request, not a
                # dead worker — record it and keep measuring, exactly
                # what a resilience run wants to observe.
                record.end_ns.append(time.monotonic_ns())
                record.error = e
                stat.add_record(record)
                tracker.release(ctx_id)
        # drain: wait briefly for in-flight requests
        deadline = time.monotonic() + 5
        acquired = 0
        while acquired < n_ctx and time.monotonic() < deadline:
            if tracker.get(timeout=0.2) is not None:
                acquired += 1

    def _stream_worker(self, stat, backend, n_ctx):
        tracker = FifoCtxIdTracker()
        tracker.reset(n_ctx)
        holders = [dict() for _ in range(n_ctx)]
        inflight: Dict[int, tuple] = {}  # key -> (record, ctx_id)
        inflight_lock = threading.Lock()
        order: List[int] = []

        def _response_key(result):
            """Pair by the echoed request id; FIFO fallback for
            backends that don't echo ids (mock)."""
            if result is not None:
                try:
                    response = result.get_response()
                    rid = (
                        response.get("id") if isinstance(response, dict)
                        else response.id
                    )
                    if rid:
                        return int(rid)
                except (AttributeError, ValueError):
                    pass
            return order[0] if order else None

        def callback(result, error):
            with inflight_lock:
                final = True
                if result is not None:
                    params = result.get_parameters()
                    final = params.get("triton_final_response", True)
                key = _response_key(result)
                if key is None or key not in inflight:
                    return  # unsolicited/late response
                record, ctx_id = inflight[key]
                record.end_ns.append(time.monotonic_ns())
                if error is not None:
                    record.error = error
                    final = True
                if final:
                    if key in order:
                        order.remove(key)
                    inflight.pop(key, None)
                    stat.add_record(record)
                    tracker.release(ctx_id)

        backend.start_stream(callback)
        counter = 0
        try:
            while not self._stop.is_set():
                ctx_id = tracker.get(timeout=0.1)
                if ctx_id is None:
                    continue
                if self._stop.is_set():
                    tracker.release(ctx_id)
                    break
                inputs, outputs, kwargs, priority, tenant = \
                    self._make_request(holders[ctx_id])
                record = RequestRecord(time.monotonic_ns(),
                                       priority=priority, tenant=tenant)
                with inflight_lock:
                    key = counter
                    counter += 1
                    inflight[key] = (record, ctx_id)
                    order.append(key)
                backend.async_stream_infer(self._model.name, inputs,
                                           outputs=outputs,
                                           request_id=str(key), **kwargs)
        finally:
            backend.stop_stream()


class RequestRateManager(LoadManager):
    """Dispatches at a fixed rate from a generated schedule, constant
    or Poisson (parity: request_rate_manager.h:57,
    request_rate_worker.h:52). Late sends are flagged `delayed`."""

    def __init__(self, *args, distribution: str = "constant", **kwargs):
        super().__init__(*args, **kwargs)
        self._distribution = distribution
        self._rate = 0.0
        self._schedule: List[float] = []

    def _generate_schedule(self, rate: float, duration_s: float) -> List[float]:
        if rate <= 0:
            return []
        offsets = []
        t = 0.0
        rng = random.Random(11)
        while t < duration_s:
            if self._distribution == "poisson":
                t += rng.expovariate(rate)
            else:
                t += 1.0 / rate
            offsets.append(t)
        return offsets

    def change_request_rate(self, rate: float,
                            duration_s: float = 3600) -> None:
        self.stop()
        self._rate = rate
        if rate <= 0:
            return
        self._schedule = self._generate_schedule(rate, duration_s)
        self._launch_schedule_workers()

    def set_custom_schedule(self, intervals_s: List[float]) -> None:
        """Absolute offsets computed from user intervals
        (CustomLoadManager parity, custom_load_manager.h:46); cycled
        when exhausted."""
        self.stop()
        offsets = []
        t = 0.0
        # repeat the interval list to cover a long window
        for _ in range(200000 // max(len(intervals_s), 1) + 1):
            for interval in intervals_s:
                t += interval
                offsets.append(t)
            if t > 3600:
                break
        self._schedule = offsets
        self._launch_schedule_workers()

    def _launch_schedule_workers(self):
        n_threads = min(self._max_threads, 8)
        self._thread_stats = [ThreadStat() for _ in range(n_threads)]
        self._threads = []
        start_ns = time.monotonic_ns() + int(0.01 * NANOS)
        for i in range(n_threads):
            thread = threading.Thread(
                target=self._worker,
                args=(self._thread_stats[i], i, n_threads, start_ns),
                daemon=True,
            )
            self._threads.append(thread)
            thread.start()

    def _worker(self, stat: ThreadStat, worker_idx: int, n_workers: int,
                start_ns: int) -> None:
        try:
            backend = self._factory.create()
        except Exception as e:
            stat.status = e
            return

        def _done(record):
            def callback(result, error):
                record.end_ns.append(time.monotonic_ns())
                if error is not None:
                    record.error = error
                stat.add_record(record)

            return callback

        try:
            idx = worker_idx
            holder: dict = {}
            while not self._stop.is_set() and idx < len(self._schedule):
                due_ns = start_ns + int(self._schedule[idx] * NANOS)
                now = time.monotonic_ns()
                delayed = False
                if now < due_ns:
                    wait = (due_ns - now) / NANOS
                    if self._stop.wait(timeout=wait):
                        break
                else:
                    delayed = (now - due_ns) > 0.01 * NANOS
                kwargs, stream, seq_step = self._sequence_step(holder)
                step = (
                    seq_step if seq_step is not None
                    else self._next_step(stream)
                )
                inputs = self._data_manager.build_inputs(stream, step)
                outputs = self._data_manager.build_outputs()
                priority, tenant = self._qos_assign()
                kwargs.update(self._qos_kwargs(priority, tenant))
                record = RequestRecord(time.monotonic_ns(), delayed=delayed,
                                       priority=priority, tenant=tenant)
                if self._async:
                    try:
                        backend.async_infer(_done(record), self._model.name,
                                            inputs, outputs=outputs,
                                            **kwargs)
                    except InferenceServerException as e:
                        # Shed at submission (pool fully ejected): one
                        # failed request, not a dead worker.
                        record.end_ns.append(time.monotonic_ns())
                        record.error = e
                        stat.add_record(record)
                else:
                    try:
                        backend.infer(self._model.name, inputs,
                                      outputs=outputs, **kwargs)
                        record.end_ns.append(time.monotonic_ns())
                    except InferenceServerException as e:
                        record.error = e
                    stat.add_record(record)
                idx += n_workers
        except Exception as e:
            stat.status = e
        finally:
            try:
                backend.close()
            except Exception:
                pass


class CustomLoadManager(RequestRateManager):
    """Replays user-provided request intervals from a file, one
    microsecond value per line (parity: custom_load_manager.h:46 /
    the --request-intervals CLI mode)."""

    def __init__(self, *args, request_intervals_file: Optional[str] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._intervals_file = request_intervals_file

    @staticmethod
    def read_intervals_file(path: str) -> List[float]:
        with open(path) as f:
            intervals = [int(line.strip()) / 1e6
                         for line in f if line.strip()]
        if not intervals:
            raise ValueError("request-intervals file '%s' is empty" % path)
        return intervals

    def start_schedule(self) -> None:
        self.set_custom_schedule(
            self.read_intervals_file(self._intervals_file))


class PeriodicConcurrencyManager(ConcurrencyManager):
    """Ramps concurrency from start to end by `step` every
    `request_period` completed requests (parity:
    periodic_concurrency_manager.h:39 — LLM-oriented)."""

    def __init__(self, *args, concurrency_start: int = 1,
                 concurrency_end: int = 8, concurrency_step: int = 1,
                 request_period: int = 10, **kwargs):
        super().__init__(*args, **kwargs)
        self._start_c = concurrency_start
        self._end_c = concurrency_end
        self._step_c = concurrency_step
        self._period = request_period
        self._ramp_thread: Optional[threading.Thread] = None

    def run_ramp(self) -> None:
        current = self._start_c
        self.change_concurrency_level(current)
        while current < self._end_c and not self._stop.is_set():
            # change_concurrency_level resets thread stats, so the
            # collected count starts from zero at every level
            if self.count_collected_requests() >= self._period:
                current = min(current + self._step_c, self._end_c)
                self.change_concurrency_level(current)
            time.sleep(0.01)
