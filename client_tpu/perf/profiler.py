"""Measurement engine: sweeps load levels, repeats measurement windows
until the last three trials are stable, computes client percentiles
and pairs server-side statistics (parity: inference_profiler.h:215,
Measure/ProfileHelper semantics incl. the last-3-trials stability rule
and window sleep)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from client_tpu.perf.load_manager import (
    ConcurrencyManager,
    LoadManager,
    RequestRateManager,
    RequestRecord,
)
from client_tpu.utils import InferenceServerException

NANOS = 1_000_000_000


class PerfStatus:
    """One stable measurement at a load level (parity: PerfStatus
    inference_profiler.h:178)."""

    def __init__(self):
        self.concurrency = 0
        self.request_rate = 0.0
        self.client_stats: Dict[str, float] = {}
        self.server_stats: Dict[str, dict] = {}
        self.latency_percentiles: Dict[int, float] = {}
        self.throughput = 0.0
        self.avg_latency_us = 0.0
        self.std_latency_us = 0.0
        self.completed_count = 0
        self.delayed_count = 0
        self.error_count = 0
        self.on_target = True
        self.records: List[RequestRecord] = []
        self.window_start_ns = 0
        self.window_end_ns = 0
        # summarized server accelerator gauges for the window:
        # {family: {"avg": x, "max": y}} (see perf.metrics_manager)
        self.tpu_metrics: Dict[str, Dict[str, float]] = {}


class MeasurementConfig:
    def __init__(
        self,
        measurement_interval_ms: int = 5000,
        measurement_mode: str = "time_windows",  # or count_windows
        measurement_request_count: int = 50,
        max_trials: int = 10,
        stability_threshold: float = 0.1,
        latency_threshold_ms: float = 0.0,
        percentile: int = 0,  # 0 = use average for stability
        batch_size: int = 1,
    ):
        self.interval_ms = measurement_interval_ms
        self.mode = measurement_mode
        self.request_count = measurement_request_count
        self.max_trials = max_trials
        self.stability = stability_threshold
        self.latency_threshold_ms = latency_threshold_ms
        self.percentile = percentile
        # Inferences per request: throughput is inferences/sec
        # (requests x batch / window), reference semantics
        # (inference_profiler.cc valid_request_count * batch_size).
        self.batch_size = batch_size


def _normalize_stats_entry(entry: Dict) -> Dict:
    """Undoes protobuf-JSON int64 stringification on the known numeric
    fields only (a generic string->int pass would corrupt `version`)."""
    out = dict(entry)
    for key in ("inference_count", "execution_count", "reject_count",
                "timeout_count", "cache_hit_count", "cache_miss_count",
                "shed_count"):
        if key in out:
            out[key] = int(out[key])
    for key in ("priority_stats", "tenant_stats"):
        if key in out:
            out[key] = [
                {name: (int(value) if name not in ("tenant",)
                        else value)
                 for name, value in row.items()}
                for row in out[key]
            ]
    sections = {}
    for name, section in dict(out.get("inference_stats", {})).items():
        sections[name] = (
            {k: int(v) for k, v in section.items()}
            if isinstance(section, dict) else section
        )
    if sections:
        out["inference_stats"] = sections
    if "batch_stats" in out:
        out["batch_stats"] = [
            {
                name: (
                    {k: int(v) for k, v in value.items()}
                    if isinstance(value, dict) else int(value)
                )
                for name, value in row.items()
            }
            for row in out["batch_stats"]
        ]
    if "pipeline_stats" in out:
        out["pipeline_stats"] = {
            name: float(value) if name == "overlap_ratio" else int(value)
            for name, value in out["pipeline_stats"].items()
        }
    if "sequence_stats" in out:
        out["sequence_stats"] = {
            name: int(value)
            for name, value in out["sequence_stats"].items()
        }
    if "stream_stats" in out:
        # Counters + nested StatisticDuration pairs (count/ns), all
        # additive — window deltas and merges treat them generically.
        out["stream_stats"] = {
            name: (
                {k: int(v) for k, v in value.items()}
                if isinstance(value, dict) else int(value)
            )
            for name, value in dict(out["stream_stats"]).items()
        }
    return out


# sequence_stats gauges pass through as window-end values in deltas
# and merges (active/backlog/slot_total are occupancy, not counters).
_SEQUENCE_GAUGES = ("active_sequences", "slot_total", "backlog_depth")


def _numeric_delta(before, after):
    """after - before over matching numeric leaves; non-numeric leaves
    (names, versions) pass through from `after`."""
    if isinstance(after, dict):
        before = before if isinstance(before, dict) else {}
        return {
            key: _numeric_delta(before.get(key), value)
            for key, value in after.items()
        }
    if isinstance(after, (int, float)) and not isinstance(after, bool):
        base = before if isinstance(before, (int, float)) \
            and not isinstance(before, bool) else 0
        # Clamp: a server-side counter reset mid-window must not
        # produce negative counts (matches the native CombineDuration).
        return max(after - base, 0)
    return after


def _accumulate_numeric(total, part):
    """total + part over numeric leaves (dict-shaped mirror of
    _numeric_delta, used when merging stable windows)."""
    if isinstance(part, dict):
        total = total if isinstance(total, dict) else {}
        return {
            key: _accumulate_numeric(total.get(key), value)
            for key, value in part.items()
        }
    if isinstance(part, (int, float)) and not isinstance(part, bool):
        base = total if isinstance(total, (int, float)) \
            and not isinstance(total, bool) else 0
        return base + part
    return part


def _accumulate_server_stats(total: Dict, part: Dict) -> Dict:
    """Sums two window-delta server_stats payloads, matching
    model_stats entries by (name, version) — _accumulate_numeric alone
    cannot merge the entry LIST (it would replace it wholesale)."""
    if not part:
        return total
    if not total:
        return part
    merged = {
        (e.get("name"), e.get("version", "")): e
        for e in total.get("model_stats", [])
    }
    for entry in part.get("model_stats", []):
        key = (entry.get("name"), entry.get("version", ""))
        prior = merged.get(key, {})
        acc = _accumulate_numeric(prior, entry)
        if "batch_stats" in entry or "batch_stats" in prior:
            by_size: Dict = {}
            for row in list(prior.get("batch_stats", [])) + list(
                    entry.get("batch_stats", [])):
                size = row.get("batch_size")
                base = by_size.get(size, {})
                summed = _accumulate_numeric(base, row)
                summed["batch_size"] = size
                by_size[size] = summed
            acc["batch_stats"] = list(by_size.values())
        for list_key, row_key in (("priority_stats", "priority_level"),
                                  ("tenant_stats", "tenant")):
            if list_key in entry or list_key in prior:
                acc[list_key] = _accumulate_keyed_list(
                    prior.get(list_key, []), entry.get(list_key, []),
                    row_key)
        seq_prior = prior.get("sequence_stats", {})
        seq_part = entry.get("sequence_stats", {})
        if seq_prior or seq_part:
            seq = (_accumulate_numeric(seq_prior, seq_part)
                   if seq_part else dict(seq_prior))
            for gauge in _SEQUENCE_GAUGES:
                if gauge in seq_part:
                    seq[gauge] = seq_part[gauge]
            acc["sequence_stats"] = seq
        pipe_prior = prior.get("pipeline_stats", {})
        pipe_part = entry.get("pipeline_stats", {})
        if pipe_prior or pipe_part:
            # _accumulate_numeric iterates the PART's keys, so a window
            # without pipeline_stats (batcher unloaded mid-run) must not
            # wipe earlier windows' counters.
            pipe = (_accumulate_numeric(pipe_prior, pipe_part)
                    if pipe_part else dict(pipe_prior))
            # Gauges and the derived ratio are not additive: keep the
            # latest window's view / recompute from summed counters.
            for gauge in ("pending_count", "inflight_count",
                          "queue_delay_us"):
                if gauge in pipe_part:
                    pipe[gauge] = pipe_part[gauge]
            fetch_ns = pipe.get("fetch_ns", 0)
            pipe["overlap_ratio"] = (
                pipe.get("overlap_ns", 0) / fetch_ns if fetch_ns else 0.0)
            acc["pipeline_stats"] = pipe
        merged[key] = acc
    return {"model_stats": list(merged.values())}


def _delta_server_stats(before: Dict, after: Dict) -> Dict:
    """Window-start/window-end statistics pairing: returns the same
    model_stats shape holding only THIS window's deltas, one entry per
    (model, version) — the top model plus ensemble composing models.

    Counters are differenced; the batcher pipeline GAUGES
    (pending_count / inflight_count / queue_delay_us) pass through as
    window-end values, and the fused-batch histogram is matched row by
    row on batch_size (a plain leaf delta cannot difference a list)."""
    out = []
    for key, entry in after.items():
        prior = before.get(key, {})
        delta = _numeric_delta(prior, entry)
        if "batch_stats" in entry:
            delta["batch_stats"] = _delta_batch_stats(
                prior.get("batch_stats", []), entry["batch_stats"])
        if "priority_stats" in entry:
            delta["priority_stats"] = _delta_keyed_list(
                prior.get("priority_stats", []), entry["priority_stats"],
                "priority_level")
        if "tenant_stats" in entry:
            delta["tenant_stats"] = _delta_keyed_list(
                prior.get("tenant_stats", []), entry["tenant_stats"],
                "tenant")
        if "pipeline_stats" in entry:
            pipe = _numeric_delta(prior.get("pipeline_stats", {}),
                                  entry["pipeline_stats"])
            for gauge in ("pending_count", "inflight_count",
                          "queue_delay_us"):
                if gauge in entry["pipeline_stats"]:
                    pipe[gauge] = entry["pipeline_stats"][gauge]
            fetch_ns = pipe.get("fetch_ns", 0)
            pipe["overlap_ratio"] = (
                pipe.get("overlap_ns", 0) / fetch_ns if fetch_ns else 0.0)
            delta["pipeline_stats"] = pipe
        if "sequence_stats" in entry:
            seq = _numeric_delta(prior.get("sequence_stats", {}),
                                 entry["sequence_stats"])
            for gauge in _SEQUENCE_GAUGES:
                if gauge in entry["sequence_stats"]:
                    seq[gauge] = entry["sequence_stats"][gauge]
            delta["sequence_stats"] = seq
        out.append(delta)
    return {"model_stats": out}


def _delta_keyed_list(before: List[Dict], after: List[Dict],
                      key: str) -> List[Dict]:
    """Row-matched deltas for repeated per-class stats (priority_stats
    keyed by priority_level, tenant_stats by tenant), dropping rows
    with no activity this window."""
    prior = {row.get(key): row for row in before}
    out = []
    for row in after:
        delta = _numeric_delta(prior.get(row.get(key), {}), row)
        delta[key] = row.get(key)
        if any(v for name, v in delta.items()
               if name != key and isinstance(v, (int, float))):
            out.append(delta)
    return out


def _accumulate_keyed_list(total: List[Dict], part: List[Dict],
                           key: str) -> List[Dict]:
    """Row-matched accumulation (merge of stable windows) for the same
    repeated per-class stats."""
    by_key: Dict = {}
    for row in list(total) + list(part):
        base = by_key.get(row.get(key), {})
        summed = _accumulate_numeric(base, row)
        summed[key] = row.get(key)
        by_key[row.get(key)] = summed
    return list(by_key.values())


def _delta_batch_stats(before: List[Dict], after: List[Dict]) -> List[Dict]:
    """Per-batch-size histogram deltas, dropping sizes this window
    never executed."""
    prior = {row.get("batch_size"): row for row in before}
    out = []
    for row in after:
        delta = _numeric_delta(prior.get(row.get("batch_size"), {}), row)
        delta["batch_size"] = row.get("batch_size")
        counts = delta.get("compute_infer", {})
        if isinstance(counts, dict) and not counts.get("count"):
            continue
        out.append(delta)
    return out


class InferenceProfiler:
    def __init__(self, manager: LoadManager, config: MeasurementConfig,
                 backend=None, model_name: str = "", verbose: bool = False,
                 metrics_manager=None, composing_models=None):
        self._manager = manager
        self._config = config
        self._backend = backend  # for server-side stats
        self._model_name = model_name
        # Ensemble composing models: their stats are snapshotted and
        # paired alongside the top model (reference
        # inference_profiler.cc:648 MergeServerSideStats).
        self._composing = list(composing_models or [])
        self._verbose = verbose
        self._metrics = metrics_manager  # perf.metrics_manager.MetricsManager
        if self._metrics is not None:
            self._metrics.start()

    # -- sweeping --------------------------------------------------------

    def profile_concurrency_range(self, start: int, end: int,
                                  step: int = 1) -> List[PerfStatus]:
        assert isinstance(self._manager, ConcurrencyManager)
        results = []
        concurrency = start
        while concurrency <= end or (end == 0 and concurrency == start):
            self._manager.change_concurrency_level(concurrency)
            status = self._profile_level()
            status.concurrency = concurrency
            results.append(status)
            if self._exceeds_latency(status):
                break
            if end == 0:
                break
            concurrency += step
        self._manager.stop()
        return results

    def profile_request_rate_range(self, start: float, end: float,
                                   step: float = 1.0) -> List[PerfStatus]:
        assert isinstance(self._manager, RequestRateManager)
        results = []
        rate = start
        while rate <= end or (end == 0 and rate == start):
            self._manager.change_request_rate(rate)
            status = self._profile_level()
            status.request_rate = rate
            results.append(status)
            if self._exceeds_latency(status):
                break
            if end == 0:
                break
            rate += step
        self._manager.stop()
        return results

    def profile_custom_intervals(self) -> List[PerfStatus]:
        """Profile one level driven by the manager's custom interval
        schedule (CustomLoadManager intervals file; for an explicit
        list call manager.set_custom_schedule first and use
        profile_single_level)."""
        assert isinstance(self._manager, RequestRateManager)
        self._manager.start_schedule()
        status = self._profile_level()
        self._manager.stop()
        return [status]

    def profile_single_level(self) -> PerfStatus:
        """Measure at whatever load the manager is already generating
        (periodic-concurrency ramp mode)."""
        return self._profile_level()

    def _exceeds_latency(self, status: PerfStatus) -> bool:
        if self._config.latency_threshold_ms <= 0:
            return False
        measured = (
            status.latency_percentiles.get(self._config.percentile,
                                           status.avg_latency_us)
            if self._config.percentile else status.avg_latency_us
        )
        return measured / 1000.0 > self._config.latency_threshold_ms

    # -- one load level --------------------------------------------------

    def _profile_level(self) -> PerfStatus:
        """Repeat measurement windows until the last three agree
        within the stability threshold on latency AND throughput
        (reference stability rule), or max_trials is hit."""
        trials: List[PerfStatus] = []
        for trial in range(self._config.max_trials):
            status = self._measure()
            self._manager.check_health()
            trials.append(status)
            if self._verbose:
                print(
                    "  trial %d: %.1f infer/sec, avg %.0f us"
                    % (trial, status.throughput, status.avg_latency_us)
                )
            if self._config.max_trials == 1:
                # Single-window modes (--request-count) measure once
                # by design; the 3-trial stability rule cannot apply.
                if status.completed_count == 0:
                    raise InferenceServerException(
                        "no valid requests recorded in the measurement "
                        "window; use a larger --measurement-interval")
                return self._merge(trials)
            if self._is_stable(trials):
                return self._merge(trials[-3:])
        if all(t.completed_count == 0 for t in trials):
            # Reference contract: a level whose every window saw no
            # completed request is an error, not a zero-stat report
            # (inference_profiler.cc "No valid requests recorded").
            raise InferenceServerException(
                "no valid requests recorded in any measurement window; "
                "use a larger --measurement-interval or "
                "--measurement-mode count_windows")
        # unstable: report the merge anyway, flagged
        merged = self._merge(trials[-3:] if len(trials) >= 3 else trials)
        merged.on_target = False
        return merged

    def _measure(self) -> PerfStatus:
        self._manager.swap_request_records()  # discard warm-up residue
        if self._metrics is not None:
            self._metrics.get_and_reset()  # drop inter-window scrapes
        stats_before = self._server_stats_snapshot()
        start_ns = time.monotonic_ns()
        if self._config.mode == "count_windows":
            deadline = time.monotonic() + self._config.interval_ms / 1000.0 * 10
            while (
                self._manager.count_collected_requests()
                < self._config.request_count
                and time.monotonic() < deadline
            ):
                time.sleep(0.005)
        else:
            # reference sleeps window * 1.2 then snapshots
            time.sleep(self._config.interval_ms / 1000.0)
        end_ns = time.monotonic_ns()
        records = self._manager.swap_request_records()
        stats_after = self._server_stats_snapshot()
        status = self._summarize(records, start_ns, end_ns)
        if stats_after is not None:
            status.server_stats = _delta_server_stats(
                stats_before or {}, stats_after)
        if self._metrics is not None:
            from client_tpu.perf.metrics_manager import summarize_metrics

            status.tpu_metrics = summarize_metrics(
                self._metrics.get_and_reset())
        return status

    def _summarize(self, records: List[RequestRecord], start_ns: int,
                   end_ns: int) -> PerfStatus:
        status = PerfStatus()
        status.window_start_ns = start_ns
        status.window_end_ns = end_ns
        status.records = records
        window_s = (end_ns - start_ns) / NANOS
        valid = [r for r in records if r.valid]
        status.completed_count = len(valid)
        status.error_count = sum(1 for r in records if r.error is not None)
        status.delayed_count = sum(1 for r in records if r.delayed)
        if not valid:
            return status
        latencies_us = np.array([r.latency_ns / 1000.0 for r in valid])
        status.avg_latency_us = float(latencies_us.mean())
        status.std_latency_us = float(latencies_us.std())
        for p in (50, 90, 95, 99):
            status.latency_percentiles[p] = float(
                np.percentile(latencies_us, p)
            )
        if self._config.percentile and self._config.percentile not in (
            50, 90, 95, 99,
        ):
            status.latency_percentiles[self._config.percentile] = float(
                np.percentile(latencies_us, self._config.percentile)
            )
        status.throughput = (
            len(valid) * self._config.batch_size / window_s
            if window_s > 0 else 0.0
        )
        return status

    def _server_stats_snapshot(self) -> Optional[Dict]:
        """Cumulative server statistics for the model and its
        composing models, keyed by (name, version). Deltas between the
        window-start and window-end snapshots isolate THIS window's
        queue/compute behavior from warmup and earlier windows
        (reference pairs start/end ModelInferenceStatistics per
        Measure, inference_profiler.cc:648)."""
        if self._backend is None or not self._model_name:
            return None
        wanted = set([self._model_name] + self._composing)
        try:  # one all-models query per snapshot (native parity)
            stats = self._backend.model_statistics("")
        except Exception:
            return None
        snapshot: Dict = {}
        for entry in stats.get("model_stats", []):
            if entry.get("name") not in wanted:
                continue
            key = (entry.get("name"), entry.get("version", ""))
            snapshot[key] = _normalize_stats_entry(entry)
        return snapshot or None

    def _is_stable(self, trials: List[PerfStatus]) -> bool:
        if len(trials) < 3:
            return False
        last = trials[-3:]
        if any(t.completed_count == 0 for t in last):
            return False
        metric = (
            (lambda t: t.latency_percentiles.get(self._config.percentile,
                                                 t.avg_latency_us))
            if self._config.percentile else (lambda t: t.avg_latency_us)
        )
        latencies = [metric(t) for t in last]
        throughputs = [t.throughput for t in last]
        for values in (latencies, throughputs):
            mean = sum(values) / 3
            if mean <= 0:
                return False
            if any(abs(v - mean) / mean > self._config.stability
                   for v in values):
                return False
        if self._config.latency_threshold_ms > 0:
            if any(
                metric(t) / 1000.0 > self._config.latency_threshold_ms
                for t in last
            ):
                return True  # over threshold: stop early, caller reports
        return True

    def _merge(self, trials: List[PerfStatus]) -> PerfStatus:
        """Merge the stable trials into one report (parity:
        MergePerfStatusReports inference_profiler.cc:648)."""
        if not trials:
            return PerfStatus()
        merged = PerfStatus()
        merged.records = [r for t in trials for r in t.records]
        merged.window_start_ns = trials[0].window_start_ns
        merged.window_end_ns = trials[-1].window_end_ns
        merged.completed_count = sum(t.completed_count for t in trials)
        merged.error_count = sum(t.error_count for t in trials)
        merged.delayed_count = sum(t.delayed_count for t in trials)
        valid = [r for r in merged.records if r.valid]
        if valid:
            latencies_us = np.array([r.latency_ns / 1000.0 for r in valid])
            merged.avg_latency_us = float(latencies_us.mean())
            merged.std_latency_us = float(latencies_us.std())
            for p in (50, 90, 95, 99):
                merged.latency_percentiles[p] = float(
                    np.percentile(latencies_us, p)
                )
            if self._config.percentile and self._config.percentile not in (
                50, 90, 95, 99,
            ):
                merged.latency_percentiles[self._config.percentile] = float(
                    np.percentile(latencies_us, self._config.percentile)
                )
        window_s = sum(
            (t.window_end_ns - t.window_start_ns) / NANOS for t in trials
        )
        merged.throughput = (
            merged.completed_count * self._config.batch_size / window_s
            if window_s > 0 else 0.0
        )
        # Per-window deltas sum across the merged windows (counts and
        # ns are additive); non-numeric fields ride through.
        merged.server_stats = {}
        for trial in trials:
            merged.server_stats = _accumulate_server_stats(
                merged.server_stats, trial.server_stats)
        families = {f for t in trials for f in t.tpu_metrics}
        for fam in families:
            windows = [t.tpu_metrics[fam] for t in trials
                       if fam in t.tpu_metrics]
            if any("delta" in w for w in windows):
                # Counter families (cache hit/miss/evictions): window
                # deltas sum across merged windows; "last" keeps the
                # final cumulative value.
                merged.tpu_metrics[fam] = {
                    "delta": sum(w.get("delta", 0.0) for w in windows),
                    "last": windows[-1].get("last", 0.0),
                }
            else:
                merged.tpu_metrics[fam] = {
                    "avg": sum(w["avg"] for w in windows) / len(windows),
                    "max": max(w["max"] for w in windows),
                }
        return merged
