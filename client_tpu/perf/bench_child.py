"""Budget-aware benchmark child process.

``bench.py`` (the orchestrator, which never imports jax) spawns this
module with an absolute wall-clock deadline.  The child owns the JAX
runtime: it initializes the platform once, serves models over gRPC
in-process, and runs staged measurements — writing a complete result
JSON to ``--out`` after *every* stage so the orchestrator always has
the best-so-far number even if the deadline kills us mid-stage.

Stages (each gated on remaining budget):
  1. jax init + ``simple`` warmup + gRPC server   -> INIT marker
  2. ``simple`` over gRPC (native C++ harness when prebuilt,
     Python harness otherwise)                    -> guaranteed number
  3. ``simple`` in-process (no RPC)               -> RPC-tax datum
  4. resnet50 warmup + gRPC with TPU shared-mem   -> headline number
  5. resnet50 in-process                          -> headline RPC tax

Methodology mirrors the reference harness: fixed measurement windows
with a last-N-trials stability rule (reference
src/c++/perf_analyzer/inference_profiler.cc Measure loop); windows are
shortened here to fit the driver's wall-clock budget.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import threading
import time

from client_tpu.perf.harness_proc import run_native

REPO = pathlib.Path(__file__).resolve().parents[2]

# Reference baselines (illustrative — docs/quick_start.md:94 and
# docs/benchmarking.md:121,75 of the reference perf_analyzer).
BASELINE_SIMPLE = 1407.84
BASELINE_RESNET = 165.8
BASELINE_INPROCESS = 19.6095  # ref --service-kind=triton_c_api row

# Regenerated TPU baselines for the BASELINE.md configs the reference
# publishes no numbers for: the round-3 measured values on this
# hardware, frozen in BASELINE.md's "Regenerated baselines" table.
# vs_baseline for these stages = improvement over that anchor.
BASELINE_R3 = {
    "bert_grpc_sysshm": 102.64,
    "ensemble_stream_grpc": 62.32,
    "llm_tokens_per_sec": 192.0,
    "llm_itl_p99_ms": 129.82,
}

# v5e single-chip bf16 peak (SURVEY §6 north-star denominator).
PEAK_BF16_FLOPS = 394e12

# (model, batch) -> (exec_ms_device, fetch_ms): corrected-probe results
# measured earlier in the same run — ~350 chained device executions
# each, not worth re-paying when two stages want the same shape.
PROBE_CACHE: dict = {}

RESULT: dict = {"stages": {}}
_OUT_PATH: pathlib.Path | None = None


def log(msg: str) -> None:
    print("[bench-child %7.1fs] %s" % (time.time() - T0, msg),
          file=sys.stderr, flush=True)


T0 = time.time()


def flush_result() -> None:
    """Atomically (re)write the full result file."""
    if _OUT_PATH is None:
        return
    tmp = _OUT_PATH.with_suffix(".tmp")
    tmp.write_text(json.dumps(RESULT))
    tmp.replace(_OUT_PATH)


# Per-stage device sampling (client_tpu.server.devstats): armed once
# the in-child core exists, every record_stage then carries the HBM
# peak observed during the stage and the XLA compiles it triggered —
# BENCH rounds finally carry a memory trajectory.
DEVICE_STATS = {"stats": None}


def set_device_stats(devstats) -> None:
    try:
        devstats.stage_sample()  # reset the baseline
        DEVICE_STATS["stats"] = devstats
    except Exception:  # noqa: BLE001 — sampling is best-effort
        DEVICE_STATS["stats"] = None


def record_stage(name: str, throughput: float, p50_us: float,
                 extra: dict | None = None) -> None:
    entry = {
        "throughput": round(throughput, 2),
        "p50_latency_us": round(p50_us, 1),
        **(extra or {}),
    }
    stats = DEVICE_STATS["stats"]
    if stats is not None:
        try:
            sample = stats.stage_sample()
            entry.setdefault("hbm_peak_bytes",
                             sample["hbm_peak_bytes"])
            entry.setdefault("compile_count", sample["compile_count"])
        except Exception:  # noqa: BLE001
            pass
    RESULT["stages"][name] = entry
    flush_result()
    log("stage %s: %.2f infer/sec, p50 %.0f us" % (name, throughput, p50_us))


def native_binary() -> pathlib.Path | None:
    binary = REPO / "native" / "build" / "perf_analyzer"
    return binary if binary.exists() else None


# When a watchdog fires, the stalled operation's done-Event is parked
# here; stages skip while it is unset (relay wedged — every device op
# queues behind the stuck one) and resume once it fires (merely slow).
RELAY_STALL: dict = {"event": None}


def relay_blocked() -> bool:
    stalled = RELAY_STALL["event"]
    if stalled is None:
        return False
    if stalled.is_set():
        RELAY_STALL["event"] = None
        log("earlier relay stall recovered — resuming stages")
        return False
    return True


def run_with_watchdog(label: str, fn, timeout_s: float):
    """Runs fn() on a daemon thread, bounded by a stall watchdog: an
    observed relay failure mode blocks device ops indefinitely, and a
    stuck call must cost one stage, not the whole bench budget. The
    stalled thread cannot be killed — its Event is parked in
    RELAY_STALL so later stages skip until it returns."""
    import threading

    done = threading.Event()
    box: dict = {}

    def _run():
        try:
            box["result"] = fn()
        except Exception as exc:  # noqa: BLE001 — re-raised below
            box["error"] = exc
        finally:
            done.set()

    threading.Thread(target=_run, daemon=True,
                     name="watchdog-%s" % label).start()
    if not done.wait(timeout_s):
        RELAY_STALL["event"] = done
        raise RuntimeError("%s stalled (relay hang?) — skipping stages "
                           "until it returns" % label)
    if "error" in box:
        raise box["error"]
    return box.get("result")


class _CompileCounter:
    """Counts XLA compiles during a window via jax_log_compiles, to
    prove the measured steady state triggers no recompiles."""

    def __init__(self) -> None:
        import logging

        self.count = 0
        outer = self

        class _Handler(logging.Handler):
            def emit(self, record):
                if "Compiling" in record.getMessage():
                    outer.count += 1

        self._handler = _Handler()
        self._logger = logging.getLogger("jax")

    def __enter__(self):
        import jax

        jax.config.update("jax_log_compiles", True)
        self._logger.addHandler(self._handler)
        return self

    def __exit__(self, *exc):
        import jax

        self._logger.removeHandler(self._handler)
        jax.config.update("jax_log_compiles", False)
        return False


def measure_model_exec_ms(core, model_name: str, batch: int,
                          trials: int = 3) -> float:
    """Median dispatch->host-fetch time of one bare model execution —
    no RPC, no batcher, fresh inputs each trial (the axon relay caches
    repeat fetches of the same array). The gap between this and the
    served p50 is the serving stack's own overhead."""
    import numpy as np

    from client_tpu.utils import triton_to_np_dtype

    model = core.repository.get(model_name, "")
    rng = np.random.default_rng(0)
    times = []
    for _ in range(trials + 1):  # first run discarded (fetch-path warm)
        inputs = {}
        for spec in model.inputs:
            shape = [d if d > 0 else 1 for d in spec.shape]
            if model.max_batch_size > 0:
                shape = [batch] + shape
            np_dtype = np.dtype(triton_to_np_dtype(spec.datatype))
            if np_dtype.kind in "iu":
                data = rng.integers(0, 8, size=shape).astype(np_dtype)
            else:
                data = rng.random(size=shape, dtype=np.float32).astype(
                    np_dtype)
            inputs[spec.name] = data
        t0 = time.perf_counter()
        outputs = model.infer(inputs, {})
        for value in outputs.values():
            np.asarray(value)
        times.append(time.perf_counter() - t0)
    times = times[1:]
    return sorted(times)[len(times) // 2] * 1000.0


def measure_model_exec_corrected(core, model_name: str, batch: int,
                                 chain: int = 32, trials: int = 5):
    """Relay-honest device step time (BASELINE.md methodology):
    dispatches ``chain`` executions back-to-back and fetches only the
    LAST output, then solves  T1 = e + f,  Tn = n*e + f  for the
    device exec time e — the fixed ~65 ms device->host round trip the
    relay adds to any naive timing drops out. Returns
    (exec_ms, fetch_ms) medians over ``trials``."""
    import numpy as np

    from client_tpu.utils import triton_to_np_dtype

    model = core.repository.get(model_name, "")
    rng = np.random.default_rng(0)
    inputs = {}
    for spec in model.inputs:
        shape = [d if d > 0 else 128 for d in spec.shape]
        if model.max_batch_size > 0:
            shape = [batch] + shape
        np_dtype = np.dtype(triton_to_np_dtype(spec.datatype))
        if np_dtype.kind in "iu":
            data = rng.integers(0, 8, size=shape).astype(np_dtype)
        else:
            data = rng.random(size=shape, dtype=np.float32).astype(np_dtype)
        inputs[spec.name] = data

    # Device-resident inputs, or every chained exec re-pays the
    # host->device upload round trip and the probe measures the relay
    # again instead of the device (the serving path reads the arena —
    # its inputs never cross the wire either).
    import jax
    import jax.numpy as jnp

    inputs = {name: jax.device_put(value) for name, value in inputs.items()}
    for value in inputs.values():  # force the uploads to complete
        np.asarray(jnp.reshape(value, (-1,))[:1])

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        outputs = None
        for _ in range(n):
            outputs = model.infer(inputs, {})
        for value in outputs.values():
            np.asarray(value)
        return time.perf_counter() - t0

    timed(1)  # warm the fetch path + any first-call compile
    execs, fetches = [], []
    for _ in range(trials):
        t1 = timed(1)
        tn = timed(chain)
        execs.append((tn - t1) / (chain - 1))
        fetches.append(t1)
    execs.sort()
    fetches.sort()
    exec_s = execs[len(execs) // 2]
    fetch_s = max(fetches[len(fetches) // 2] - exec_s, 0.0)
    if exec_s < 5e-5:
        # Relay jitter swamped the chain: the difference method can't
        # resolve device time this small — report unmeasurable rather
        # than a garbage MFU.
        raise RuntimeError(
            "device exec below measurement floor (%.3f ms; relay "
            "jitter dominates)" % (exec_s * 1000))
    return exec_s * 1000.0, fetch_s * 1000.0


def fusion_stats(core, model_name: str):
    """Statistics snapshot for fusion + pipeline evidence (Triton
    semantics: inference_count counts batch rows, execution_count
    counts model executions; ratio < 0.5 proves the dynamic batcher
    fused). Carries the fused-batch-size histogram and the batcher's
    compute/fetch overlap counters so window deltas land in the bench
    JSON."""
    try:
        stats = core.model_statistics(model_name)
        entry = stats.model_stats[0]
        pipe = entry.pipeline_stats
        return {
            "inference_count": int(entry.inference_count),
            "execution_count": int(entry.execution_count),
            "batch_hist": {
                int(row.batch_size): int(row.compute_infer.count)
                for row in entry.batch_stats
            },
            "fetch_ns": int(pipe.fetch_ns),
            "overlap_ns": int(pipe.overlap_ns),
            "pending_count": int(pipe.pending_count),
            "inflight_count": int(pipe.inflight_count),
            "queue_delay_us": int(pipe.queue_delay_us),
        }
    except Exception:  # noqa: BLE001 — evidence, never a failure
        return None


def cache_stats(core, model_name: str):
    """Response-cache counters for bench evidence (hits never execute;
    the hit/miss split plus execution_count proves both the replay hit
    ratio and single-flight dedup)."""
    try:
        stats = core.model_statistics(model_name)
        entry = stats.model_stats[0]
        return {
            "inference_count": int(entry.inference_count),
            "execution_count": int(entry.execution_count),
            "cache_hit_count": int(entry.cache_hit_count),
            "cache_miss_count": int(entry.cache_miss_count),
        }
    except Exception:  # noqa: BLE001 — evidence, never a failure
        return None


def run_cache_measure(core, model_name: str = "simple_cache",
                      hot_set: int = 64, threads: int = 2,
                      warm_s: float = 2.0, unique: int = 2048,
                      burst: int = 16) -> dict:
    """Hot-set replay measurement for the response cache. Three
    phases against the in-process core (no RPC, so the server-side
    cost difference is what gets measured):

    * cold — every request content-unique, so every one misses and
      rides the dynamic batcher (gather window + execute + insert);
    * warm — the same ``hot_set`` requests replayed for ``warm_s``
      after one priming pass: every request hits and bypasses the
      batcher entirely (hash + lookup + proto copy);
    * burst — ``burst`` threads fire ONE identical fresh request
      simultaneously: single-flight must coalesce them onto exactly
      one model execution.
    """
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    def request(seed: int):
        a = np.full((1, 16), seed, dtype=np.int32)
        b = np.arange(16, dtype=np.int32).reshape(1, 16) + seed
        t0 = InferInput("INPUT0", [1, 16], "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [1, 16], "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=model_name,
                                     inputs=[t0, t1], outputs=None)

    def closed_loop(request_slices, duration_s=None):
        """One closed-loop worker per slice; each worker walks ITS OWN
        request list (no shared lock in the issue path — a shared
        iterator lock convoys with the GIL and measures the harness,
        not the server). Returns (throughput, p50_us)."""
        latencies: list = []
        merge = _threading.Lock()

        def worker(slice_requests):
            local = []
            for req in slice_requests:
                t_start = time.monotonic_ns()
                core.infer(req)
                local.append(time.monotonic_ns() - t_start)
                if duration_s is not None \
                        and time.monotonic() - t_phase0 >= duration_s:
                    break
            with merge:
                latencies.extend(local)

        t_phase0 = time.monotonic()
        pool = [_threading.Thread(target=worker, args=(s,))
                for s in request_slices]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        elapsed = time.monotonic() - t_phase0
        if not latencies or elapsed <= 0:
            return 0.0, 0.0
        latencies.sort()
        p50_us = latencies[len(latencies) // 2] / 1000.0
        return len(latencies) / elapsed, p50_us

    # -- cold: `unique` never-repeating requests (all misses),
    #    pre-partitioned across the workers
    cold_requests = [request(1_000_000 + i) for i in range(unique)]
    cold_slices = [cold_requests[i::threads] for i in range(threads)]
    before_cold = cache_stats(core, model_name)
    cold_tput, cold_p50 = closed_loop(cold_slices)

    # -- warm: prime the hot set once, then replay it for warm_s
    #    (each worker cycles the hot set from its own offset)
    hot_requests = [request(2_000_000 + i) for i in range(hot_set)]
    for req in hot_requests:
        core.infer(req)
    rounds = max(1, int(50_000 * warm_s) // max(hot_set, 1))
    warm_slices = [
        (hot_requests[i % hot_set:] + hot_requests[:i % hot_set]) * rounds
        for i in range(threads)
    ]
    before_warm = cache_stats(core, model_name)
    warm_tput, warm_p50 = closed_loop(warm_slices, duration_s=warm_s)
    after_warm = cache_stats(core, model_name)

    # -- burst: single-flight dedup on one fresh request
    before_burst = cache_stats(core, model_name)
    burst_request = request(3_000_000)
    barrier = _threading.Barrier(burst)

    def burst_worker():
        barrier.wait()
        core.infer(burst_request)

    pool = [_threading.Thread(target=burst_worker) for _ in range(burst)]
    for thread in pool:
        thread.start()
    for thread in pool:
        thread.join()
    after_burst = cache_stats(core, model_name)

    result = {
        "hot_set": hot_set,
        "concurrency": threads,
        "cold_miss_tput": round(cold_tput, 2),
        "cold_miss_p50_us": round(cold_p50, 1),
        "warm_hit_tput": round(warm_tput, 2),
        "warm_hit_p50_us": round(warm_p50, 1),
    }
    if cold_tput > 0:
        result["warm_vs_cold_speedup"] = round(warm_tput / cold_tput, 2)
    if before_warm and after_warm:
        d_hit = (after_warm["cache_hit_count"]
                 - before_warm["cache_hit_count"])
        d_miss = (after_warm["cache_miss_count"]
                  - before_warm["cache_miss_count"])
        if d_hit + d_miss:
            result["warm_hit_ratio"] = round(d_hit / (d_hit + d_miss), 4)
    if before_cold and before_warm:
        result["cold_misses"] = (before_warm["cache_miss_count"]
                                 - before_cold["cache_miss_count"])
    if before_burst and after_burst:
        result["singleflight_burst"] = burst
        result["singleflight_executions"] = (
            after_burst["execution_count"]
            - before_burst["execution_count"])
    return result


def qos_stats(core, model_name: str):
    """Per-priority QoS counters for bench evidence (success / reject
    / timeout / shed per class plus cumulative queue time)."""
    try:
        stats = core.model_statistics(model_name)
        entry = stats.model_stats[0]
        return {
            int(row.priority_level): {
                "success": int(row.success_count),
                "rejected": int(row.reject_count),
                "timed_out": int(row.timeout_count),
                "shed": int(row.shed_count),
                "queue_ns": int(row.queue_ns),
            }
            for row in entry.priority_stats
        }
    except Exception:  # noqa: BLE001 — evidence, never a failure
        return None


def run_qos_measure(core, model_name: str = "qos_bench",
                    exec_delay_s: float = 0.01,
                    bulk_workers: int = 8,
                    foreground_threads: int = 1,
                    measure_s: float = 4.0) -> dict:
    """Multi-tenant overload measurement: priority-2 bulk saturates a
    bounded queue while a small priority-1 foreground keeps sending.

    The p99 gate divides two tail statistics measured in-process on a
    small CI box (~2 cores), so the setup minimizes self-inflicted
    scheduler noise: total thread count stays low (8 bulk workers
    against a 4-deep queue saturate it just as hard as 16 against 8 —
    admitted submitters block inside ``core.infer``, so workers beyond
    resident capacity only add GIL churn), bulk protos are prebuilt,
    and the 4 s loaded window puts ~250 samples behind the p99 so it
    is not an interpolation between the two worst stragglers.

    Four phases against a purpose-built slow QoS model (AddSub + a
    fixed per-execution delay so the queue actually fills on CPU,
    max_queue_size 8, two priority classes, shed watermark 0.9):

    * baseline — priority-1 closed loop alone: unloaded p50/p99;
    * overload — an OverloadScenario bulk burst (priority 2, tenant
      "bulk") saturates the queue while the same priority-1 loop runs:
      priority-1 p99 and goodput under saturation, bulk reject/shed
      accounting from the per-priority statistics;
    * fusion parity — a c16 single-class run vs a c16 mixed-priority
      run: execution counts must match within 10%, proving QoS
      ordering costs dispatch order, not batch efficiency.
    """
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.models.add_sub import AddSub
    from client_tpu.server.chaos import OverloadScenario
    from client_tpu.utils import InferenceServerException

    class _SlowQoS(AddSub):
        # Sized so a modest closed-loop bulk pool actually saturates
        # the queue on CPU: in-flight capacity is pipeline_depth x
        # preferred = 4 rows, so 8 bulk workers keep the 4-deep queue
        # hard-full (resident capacity is queue 4 + in-flight 4) —
        # while pipeline_depth 2 leaves enough dispatch slack that a
        # priority-1 arrival rides the next execution instead of
        # waiting out a serialized pipe (the 2x p99 gate).
        def __init__(self):
            super().__init__(name=model_name, datatype="INT32",
                             shape=(16,))
            self.max_batch_size = 4
            self.dynamic_batching = True
            self.preferred_batch_sizes = [2]
            self.max_queue_delay_us = 1000
            self.pipeline_depth = 2
            self.max_queue_size = 4
            self.priority_levels = 2
            self.default_priority_level = 2
            self.shed_watermark = 0.9

        def infer(self, inputs, parameters=None):
            time.sleep(exec_delay_s)
            return super().infer(inputs, parameters)

    core.repository.add_factory(model_name, _SlowQoS)
    core.repository.load(model_name)

    def request(priority: int, tenant: str, seed: int):
        a = np.full((1, 16), seed % 997, dtype=np.int32)
        b = np.arange(16, dtype=np.int32).reshape(1, 16)
        t0 = InferInput("INPUT0", [1, 16], "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [1, 16], "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(
            model_name=model_name, inputs=[t0, t1], outputs=None,
            priority=priority, parameters={"tenant": tenant})

    def p1_loop(duration_s: float) -> dict:
        """Closed-loop priority-1 foreground: latencies + goodput."""
        latencies: list = []
        errors = [0]
        merge = _threading.Lock()

        def worker(index: int):
            local, failed = [], 0
            deadline = time.monotonic() + duration_s
            seed = index * 100_000
            while time.monotonic() < deadline:
                req = request(1, "interactive", seed)
                seed += 1
                t_start = time.monotonic_ns()
                try:
                    core.infer(req)
                    local.append(time.monotonic_ns() - t_start)
                except InferenceServerException:
                    failed += 1
            with merge:
                latencies.extend(local)
                errors[0] += failed

        pool = [_threading.Thread(target=worker, args=(i,))
                for i in range(foreground_threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        if not latencies:
            return {"p50_us": 0.0, "p99_us": 0.0, "completed": 0,
                    "errors": errors[0], "goodput_pct": 0.0}
        arr = np.array(latencies, dtype=float) / 1000.0
        total = len(latencies) + errors[0]
        return {
            "p50_us": round(float(np.percentile(arr, 50)), 1),
            "p99_us": round(float(np.percentile(arr, 99)), 1),
            "completed": len(latencies),
            "errors": errors[0],
            "goodput_pct": round(len(latencies) / total * 100.0, 2),
        }

    # Bulk protos are PREBUILT and cycled: the burst's job is queue
    # pressure, not allocation churn — building numpy tensors + a
    # proto per submit at hundreds/s steals GIL slices from the very
    # p1 tail the gate measures. Sharing protos across submitter
    # threads is safe on the direct-core path (core never mutates a
    # caller-owned request; the model has no response cache, so
    # identical payloads cannot coalesce).
    bulk_pool = [request(2, "bulk", 500_000 + i) for i in range(32)]
    bulk_seed = [0]
    bulk_lock = _threading.Lock()

    def bulk_submit():
        with bulk_lock:
            bulk_seed[0] += 1
            seed = bulk_seed[0]
        core.infer(bulk_pool[seed % len(bulk_pool)])

    # -- interleaved baseline/overload rounds. The gate divides two
    # p99s measured on a shared, throttled CI box where a single
    # scheduler stall can double one window's tail, so each statistic
    # is the MEDIAN of three short windows, and unloaded/loaded
    # windows alternate (B0 L0 B1 L1 B2 L2) so slow box drift lands on
    # both sides of the ratio — the same interleaved-medians
    # discipline run_tracing_measure uses for its overhead gate. A
    # short discarded warmup absorbs numpy/JAX lazy-init first.
    # Pacing: 0.75x the NOMINAL service rate (pipeline_depth x
    # preferred / exec_delay = 400 rows/s) — dispatch/GIL overhead
    # puts the real rate nearer half that, so this is still ~1.5x
    # effective overpressure: the queue sits hard-full for the whole
    # loaded window with sheds to spare, but the excess — every
    # over-rate submission is an insta-shed exception burning the GIL
    # — stays bounded so the run measures QoS, not scheduler thrash.
    rounds = 3
    base_window_s = measure_s * 0.35
    loaded_window_s = measure_s * 0.45
    service_rate = 2 * 2 / exec_delay_s
    p1_loop(0.5)  # warmup, discarded
    before = qos_stats(core, model_name) or {}
    base_rounds, loaded_rounds = [], []
    burst = {"submitted": 0, "rejected": 0}
    for round_index in range(rounds):
        base_rounds.append(p1_loop(base_window_s))
        scenario = OverloadScenario(
            bulk_submit, rate=0.75 * service_rate, burst_after_s=0.0,
            burst_duration_s=loaded_window_s + 0.5,
            workers=bulk_workers, seed=11 + round_index).start()
        time.sleep(0.3)  # let the burst fill the queue first
        loaded_rounds.append(p1_loop(loaded_window_s))
        scenario.stop()
        for key, value in scenario.stats().items():
            burst[key] += value
        time.sleep(0.2)  # drain the residual backlog between rounds
    after = qos_stats(core, model_name) or {}

    def med(windows, key: str) -> float:
        return round(float(np.median([w[key] for w in windows])), 1)

    baseline = {"p50_us": med(base_rounds, "p50_us"),
                "p99_us": med(base_rounds, "p99_us")}
    completed = sum(w["completed"] for w in loaded_rounds)
    failed = sum(w["errors"] for w in loaded_rounds)
    loaded = {
        "p50_us": med(loaded_rounds, "p50_us"),
        "p99_us": med(loaded_rounds, "p99_us"),
        "completed": completed,
        "errors": failed,
        "goodput_pct": round(
            completed / (completed + failed) * 100.0, 2)
        if completed + failed else 0.0,
    }

    def delta(level: int, key: str) -> int:
        return (after.get(level, {}).get(key, 0)
                - before.get(level, {}).get(key, 0))

    # -- fusion parity: single-class vs mixed-priority c16
    def fusion_run(mixed: bool) -> float:
        stats_before = fusion_stats(core, model_name)
        pool = []
        for i in range(16):
            priority = 1 if (mixed and i % 2 == 0) else 2
            def worker(p=priority, offset=i):
                for j in range(8):
                    try:
                        core.infer(request(p, "fusion", 800_000
                                           + offset * 100 + j))
                    except InferenceServerException:
                        pass
            pool.append(_threading.Thread(target=worker))
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        stats_after = fusion_stats(core, model_name)
        if not stats_before or not stats_after:
            return 0.0
        d_exec = (stats_after["execution_count"]
                  - stats_before["execution_count"])
        d_infer = (stats_after["inference_count"]
                   - stats_before["inference_count"])
        return d_exec / d_infer if d_infer else 0.0

    fusion_single = fusion_run(mixed=False)
    fusion_mixed = fusion_run(mixed=True)

    result = {
        "bulk_workers": bulk_workers,
        "p1_unloaded_p50_us": baseline["p50_us"],
        "p1_unloaded_p99_us": baseline["p99_us"],
        "p1_loaded_p50_us": loaded["p50_us"],
        "p1_loaded_p99_us": loaded["p99_us"],
        "p1_completed": loaded["completed"],
        "p1_tput": round(
            loaded["completed"] / (rounds * loaded_window_s), 2),
        "p1_errors": loaded["errors"],
        "p1_goodput_pct": loaded["goodput_pct"],
        "bulk_submitted": burst["submitted"],
        "bulk_rejected": burst["rejected"],
        "bulk_server_rejects": delta(2, "rejected"),
        "bulk_server_sheds": delta(2, "shed"),
        "p1_server_sheds": delta(1, "shed"),
        "fusion_ratio_single_class": round(fusion_single, 4),
        "fusion_ratio_mixed": round(fusion_mixed, 4),
    }
    if baseline["p99_us"]:
        result["p1_p99_vs_unloaded"] = round(
            loaded["p99_us"] / baseline["p99_us"], 2)
    if fusion_single:
        result["fusion_mixed_vs_single"] = round(
            fusion_mixed / fusion_single, 3)
    return result


def replica_stats(core, model_name: str):
    """Replica-set health + lifecycle counters for bench evidence."""
    try:
        stats = core.model_statistics(model_name)
        entry = stats.model_stats[0]
        return {
            "healthy": int(entry.healthy_replicas),
            "total": int(entry.total_replicas),
            "ejected": sum(int(r.ejected_count)
                           for r in entry.replica_stats),
            "readmitted": sum(int(r.readmitted_count)
                              for r in entry.replica_stats),
            "per_replica_execs": {
                int(r.replica_index): int(r.execution_count)
                for r in entry.replica_stats},
        }
    except Exception:  # noqa: BLE001 — evidence, never a failure
        return None


def run_replica_measure(core, model_name: str = "replica_bench",
                        exec_delay_s: float = 0.004,
                        threads: int = 8,
                        measure_s: float = 2.0) -> dict:
    """Replica serving measurement: data-parallel scaling plus the
    degrade-one blast-radius timeline.

    Phase 1 — scaling: the same slow model (AddSub + a fixed
    per-execution delay so replica parallelism, not numpy speed, is
    what's measured) served with 1 replica vs 4 replicas under an
    identical closed loop. A single replica's device queue serializes
    executions, so throughput is delay-bound (~1/exec_delay); 4
    replicas run 4 queues concurrently. Acceptance: >= 2.5x.

    Phase 2 — degrade-one: replica 2 of 4 is hard-degraded mid-run via
    a replica-targeted DegradeOneScenario (every execution on it
    fails). The router re-dispatches in-flight failures to healthy
    siblings (goodput stays 100%), the breaker ejects the replica
    (throughput degrades toward 3/4), the scenario heals the fault,
    and the supervisor readmits after a canary — throughput must
    recover to within 20% of the pre-fault rate.
    """
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.models.add_sub import AddSub
    from client_tpu.server.chaos import DegradeOneScenario
    from client_tpu.utils import InferenceServerException

    def slow_replica_factory(name: str, count: int):
        class _SlowReplica(AddSub):
            # Direct path (no dynamic batcher): every request is one
            # routed execution, so the scaling ratio reads the router,
            # not the gather window. Recovery knobs are tight so the
            # degrade phase observes eject -> readmit inside its
            # windows.
            def __init__(self):
                super().__init__(name=name, datatype="INT32",
                                 shape=(16,))
                self.instance_group_count = count
                self.replica_watchdog_us = 2_000_000
                self.replica_failure_threshold = 3
                self.replica_recovery_s = 0.3

            def infer(self, inputs, parameters=None):
                time.sleep(exec_delay_s)
                return super().infer(inputs, parameters)

        return _SlowReplica

    def request(name: str, seed: int):
        a = np.full((16,), seed % 997, dtype=np.int32)
        b = np.arange(16, dtype=np.int32)
        t0 = InferInput("INPUT0", [16], "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [16], "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=name, inputs=[t0, t1],
                                     outputs=None)

    def closed_loop(name: str, duration_s: float) -> dict:
        latencies: list = []
        errors = [0]
        merge = _threading.Lock()

        def worker(index: int):
            local, failed = [], 0
            deadline = time.monotonic() + duration_s
            seed = index * 100_000
            while time.monotonic() < deadline:
                req = request(name, seed)
                seed += 1
                t_start = time.monotonic_ns()
                try:
                    core.infer(req)
                    local.append(time.monotonic_ns() - t_start)
                except InferenceServerException:
                    failed += 1
            with merge:
                latencies.extend(local)
                errors[0] += failed

        pool = [_threading.Thread(target=worker, args=(i,))
                for i in range(threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        completed = len(latencies)
        total = completed + errors[0]
        return {
            "tput": completed / duration_s if duration_s else 0.0,
            "p50_us": round(float(np.percentile(
                np.array(latencies, dtype=float) / 1000.0, 50)), 1)
            if latencies else 0.0,
            "completed": completed,
            "errors": errors[0],
            "goodput_pct": round(completed / total * 100.0, 2)
            if total else 0.0,
        }

    # -- phase 1: scaling, 1 vs 4 replicas --------------------------------
    name1, name4 = model_name + "1", model_name + "4"
    core.repository.add_factory(name1, slow_replica_factory(name1, 1))
    core.repository.add_factory(name4, slow_replica_factory(name4, 4))
    core.repository.load(name1)
    core.repository.load(name4)
    closed_loop(name1, 0.3)  # warmup, discarded
    single = closed_loop(name1, measure_s)
    closed_loop(name4, 0.3)  # warmup: instantiates the replica set
    quad = closed_loop(name4, measure_s)

    # -- phase 2: degrade replica 2 of 4 mid-run, then heal ---------------
    before = replica_stats(core, name4) or {}
    prefault = closed_loop(name4, measure_s)
    scenario = DegradeOneScenario(
        replica="%s:2" % name4, kill_after_s=0.0,
        heal_after_s=measure_s + 0.5).start()
    scenario.killed.wait(timeout=2.0)
    degraded = closed_loop(name4, measure_s)
    scenario.healed.wait(timeout=measure_s + 5.0)
    scenario.stop()
    # Give the supervisor one recovery period to canary + readmit.
    mid = replica_stats(core, name4) or {}
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        snap = replica_stats(core, name4)
        if snap and snap["readmitted"] > before.get("readmitted", 0):
            break
        time.sleep(0.1)
    recovered = closed_loop(name4, measure_s)
    after = replica_stats(core, name4) or {}

    result = {
        "exec_delay_ms": exec_delay_s * 1000.0,
        "concurrency": threads,
        "tput_1": round(single["tput"], 2),
        "p50_1_us": single["p50_us"],
        "tput_4": round(quad["tput"], 2),
        "p50_4_us": quad["p50_us"],
        "prefault_tput": round(prefault["tput"], 2),
        "degraded_tput": round(degraded["tput"], 2),
        "recovered_tput": round(recovered["tput"], 2),
        "degrade_goodput_pct": degraded["goodput_pct"],
        "degrade_errors": degraded["errors"],
        "healthy_during_degrade": mid.get("healthy"),
        "ejections": (after.get("ejected", 0)
                      - before.get("ejected", 0)),
        "readmissions": (after.get("readmitted", 0)
                         - before.get("readmitted", 0)),
    }
    if single["tput"]:
        result["scaling_4v1"] = round(quad["tput"] / single["tput"], 2)
    if prefault["tput"]:
        result["recovery_vs_prefault"] = round(
            recovered["tput"] / prefault["tput"], 3)
    return result


def run_mesh_measure(core, model_name: str = "mesh_bench",
                     exec_delay_s: float = 0.004,
                     threads: int = 8,
                     measure_s: float = 1.5) -> dict:
    """Mesh-slice serving measurement (docs/sharded_serving.md):
    slice-replica scaling plus the kill-one-chip blast-radius
    timeline.

    Phase 1 — scaling: a delay-bound model declaring a ``shard_mesh``
    served as 1 slice vs 2 slices (each slice ``tp=width`` devices)
    under an identical closed loop. Each slice runs its own device
    queue, so 2 slices sustain ~2x the fused-call rate of 1.

    Phase 2 — kill one chip: chaos ``device=<member of slice 0>``
    fails every execution that touches the chip. The router masks the
    failures (bounded re-dispatch to the sibling slice — goodput stays
    100%), the breaker ejects the WHOLE slice, the chip heals, and the
    supervisor re-initializes + canaries the slice back in.
    """
    import threading as _threading

    import jax
    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.models.add_sub import AddSub
    from client_tpu.server import chaos as chaos_mod
    from client_tpu.utils import InferenceServerException

    ndev = len(jax.devices())
    width = 4 if ndev >= 8 else 2
    if ndev < 2 * width:
        raise RuntimeError(
            "mesh measure needs %d devices (2 slices x tp=%d), have %d"
            % (2 * width, width, ndev))

    def slice_factory(name: str, count: int):
        class _SlowSlice(AddSub):
            # Direct path, sharded instance group: every request is
            # one fused sharded call on a slice's device queue. The
            # fixed delay stands in for the sharded XLA program, so
            # the scaling ratio reads slice parallelism.
            instance_group_count = count
            shard_mesh = {"tp": width}

            def __init__(self, mesh=None):
                super().__init__(name=name, datatype="INT32",
                                 shape=(16,))
                self.mesh = mesh
                self.replica_watchdog_us = 2_000_000
                self.replica_failure_threshold = 3
                self.replica_recovery_s = 0.3

            def infer(self, inputs, parameters=None):
                time.sleep(exec_delay_s)
                return super().infer(inputs, parameters)

        return _SlowSlice

    def request(name: str, seed: int):
        a = np.full((16,), seed % 997, dtype=np.int32)
        b = np.arange(16, dtype=np.int32)
        t0 = InferInput("INPUT0", [16], "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [16], "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=name, inputs=[t0, t1],
                                     outputs=None)

    def closed_loop(name: str, duration_s: float) -> dict:
        latencies: list = []
        errors = [0]
        merge = _threading.Lock()

        def worker(index: int):
            local, failed = [], 0
            deadline = time.monotonic() + duration_s
            seed = index * 100_000
            while time.monotonic() < deadline:
                req = request(name, seed)
                seed += 1
                t_start = time.monotonic_ns()
                try:
                    core.infer(req)
                    local.append(time.monotonic_ns() - t_start)
                except InferenceServerException:
                    failed += 1
            with merge:
                latencies.extend(local)
                errors[0] += failed

        pool = [_threading.Thread(target=worker, args=(i,))
                for i in range(threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        completed = len(latencies)
        total = completed + errors[0]
        return {
            "tput": completed / duration_s if duration_s else 0.0,
            "p50_us": round(float(np.percentile(
                np.array(latencies, dtype=float) / 1000.0, 50)), 1)
            if latencies else 0.0,
            "completed": completed,
            "errors": errors[0],
            "goodput_pct": round(completed / total * 100.0, 2)
            if total else 0.0,
        }

    # -- phase 1: slice scaling, 1 vs 2 slices ----------------------------
    name1, name2 = model_name + "1", model_name + "2"
    core.repository.add_factory(name1, slice_factory(name1, 1))
    core.repository.add_factory(name2, slice_factory(name2, 2))
    core.repository.load(name1)
    core.repository.load(name2)
    closed_loop(name1, 0.3)  # warmup, discarded
    single = closed_loop(name1, measure_s)
    closed_loop(name2, 0.3)  # warmup: instantiates the slice set
    double = closed_loop(name2, measure_s)

    # -- phase 2: kill one chip of slice 0 mid-load, then heal ------------
    before = replica_stats(core, name2) or {}
    # Slice 0 owns devices [0, width): failing chip 0 must eject the
    # whole slice while the sibling slice masks every request.
    chaos_mod.configure(chaos_mod.ChaosConfig(error_rate=1.0, device=0))
    try:
        degraded = closed_loop(name2, measure_s)
        mid = replica_stats(core, name2) or {}
    finally:
        chaos_mod.configure(None)  # chip healed
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        snap = replica_stats(core, name2)
        if snap and snap["readmitted"] > before.get("readmitted", 0):
            break
        time.sleep(0.1)
    after = replica_stats(core, name2) or {}

    result = {
        "exec_delay_ms": exec_delay_s * 1000.0,
        "concurrency": threads,
        "slice_width": width,
        "tput_1slice": round(single["tput"], 2),
        "p50_1slice_us": single["p50_us"],
        "tput_2slice": round(double["tput"], 2),
        "p50_2slice_us": double["p50_us"],
        "degraded_tput": round(degraded["tput"], 2),
        "degrade_goodput_pct": degraded["goodput_pct"],
        "degrade_errors": degraded["errors"],
        "healthy_during_degrade": mid.get("healthy"),
        "ejections": (after.get("ejected", 0)
                      - before.get("ejected", 0)),
        "readmissions": (after.get("readmitted", 0)
                         - before.get("readmitted", 0)),
    }
    if single["tput"]:
        result["scaling_2v1"] = round(
            double["tput"] / single["tput"], 2)
    return result


def run_autoscale_measure(core, model_name: str = "autoscale_bench",
                          exec_delay_s: float = 0.02,
                          low_rate: float = 20.0,
                          high_rate: float = 200.0,
                          low_s: float = 1.5, high_s: float = 3.0,
                          drain_s: float = 6.0) -> dict:
    """Autoscale-controller measurement: a 10x diurnal load swing
    replayed through the chaos OverloadScenario trace mode against a
    controller-governed model, with a mid-swing replica kill.

    The model is AddSub + a fixed per-execution delay (so capacity is
    replica-bound on CPU: one replica serves preferred/exec_delay
    rows/s), governed min 1 / max 4 with tight cooldowns. The trace
    is low -> 10x high -> low; the controller must grow the fleet
    through the canaried path during the high stage and drain it back
    after, while a priority-1 foreground closed loop measures the
    latency the SLO gate reads. During the high stage one serving
    replica is chaos-killed: the PR-8 masking (redispatch + ejection)
    must keep foreground goodput at 100% while the controller's
    canary keeps chaos-free replacements coming.

    Returns the smoke's evidence: foreground p50/p99/errors, the
    configured SLO target, replica-seconds consumed vs a
    max-scale-always baseline over the same window, scale events by
    direction, and the flight-recorded decision labels."""
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.models.add_sub import AddSub
    from client_tpu.server import chaos as chaos_mod
    from client_tpu.server.chaos import OverloadScenario
    from client_tpu.utils import InferenceServerException

    slo_p99_us = 250_000

    class _AutoscaleBench(AddSub):
        # One replica's service rate is preferred_batch / exec_delay
        # = 100 rows/s, so the 20/s low stage idles one replica and
        # the 200/s high stage needs the fleet — the controller has
        # to actually scale for the p99 gate to hold.
        def __init__(self):
            super().__init__(name=model_name, datatype="INT32",
                             shape=(16,))
            self.max_batch_size = 2
            self.dynamic_batching = True
            self.preferred_batch_sizes = [2]
            self.max_queue_delay_us = 1000
            self.max_queue_size = 64
            self.priority_levels = 2
            self.default_priority_level = 2
            self.shed_watermark = 0.95
            self.instance_group_count = 1
            self.instance_group_kind = "cpu"
            self.replica_failure_threshold = 3
            self.replica_recovery_s = 0.5
            self.slo_p99_latency_us = slo_p99_us
            self.slo_availability = 0.999
            self.autoscale_min_replicas = 1
            self.autoscale_max_replicas = 4
            self.autoscale_interval_s = 0.1
            self.autoscale_queue_high = 1.0
            self.autoscale_up_cooldown_s = 0.2
            self.autoscale_down_cooldown_s = 0.6

        def infer(self, inputs, parameters=None):
            time.sleep(exec_delay_s)
            return super().infer(inputs, parameters)

    core.repository.add_factory(model_name, _AutoscaleBench)
    core.load_model(model_name, warmup=False)  # starts the controller

    def request(priority: int, seed: int):
        a = np.full((1, 16), seed % 997, dtype=np.int32)
        b = np.arange(16, dtype=np.int32).reshape(1, 16)
        t0 = InferInput("INPUT0", [1, 16], "INT32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [1, 16], "INT32")
        t1.set_data_from_numpy(b)
        return get_inference_request(
            model_name=model_name, inputs=[t0, t1], outputs=None,
            priority=priority, parameters={"tenant": "bulk"})

    core.infer(request(1, 0))  # wake batcher + replica set
    replica_set = core._replica_sets[model_name]

    bulk_seed = [0]
    bulk_lock = _threading.Lock()

    def submit_bulk():
        with bulk_lock:
            bulk_seed[0] += 1
            seed = bulk_seed[0]
        core.infer(request(2, seed))

    controller_t0 = core.autoscaler.snapshot().get(model_name, {})
    seconds_t0 = controller_t0.get("replica_seconds", 0.0)
    window_t0 = time.monotonic()
    peak = [1]

    latencies: list = []
    fg_errors = [0]
    fg_stop = _threading.Event()

    def foreground():
        seed = 10_000_000
        while not fg_stop.is_set():
            seed += 1
            t_start = time.monotonic_ns()
            try:
                core.infer(request(1, seed))
                latencies.append(time.monotonic_ns() - t_start)
            except InferenceServerException:
                fg_errors[0] += 1
            peak[0] = max(peak[0], replica_set.count)

    fg_thread = _threading.Thread(target=foreground, daemon=True)
    fg_thread.start()

    scenario = OverloadScenario(
        submit_bulk, workers=8, seed=11,
        trace=[(low_rate, low_s), (high_rate, high_s),
               (low_rate, low_s)])
    scenario.start()

    # Mid-swing replica kill: wait for the high stage to be underway
    # and the fleet grown, then hard-fail one SERVING replica for a
    # bounded slice — the foreground must not see a single error.
    kill = {"fired": False, "errors_before": None}
    kill_deadline = time.monotonic() + low_s + high_s
    while time.monotonic() < kill_deadline:
        if replica_set.count >= 2:
            victim = replica_set.replicas[0].index
            kill["errors_before"] = fg_errors[0]
            kill["fired"] = True
            chaos_mod.configure(chaos_mod.ChaosConfig(
                error_rate=1.0,
                replica="%s:%d" % (model_name, victim)))
            time.sleep(0.8)
            chaos_mod.configure(None)
            break
        time.sleep(0.05)

    scenario.finished.wait(low_s + high_s + low_s + 30.0)
    scenario.stop()
    fg_stop.set()
    fg_thread.join(timeout=10)

    # Quiet tail: the controller must drain the fleet back down.
    drain_deadline = time.monotonic() + drain_s
    while time.monotonic() < drain_deadline:
        if replica_set.count <= 1:
            break
        time.sleep(0.1)
    window_s = time.monotonic() - window_t0

    controller = core.autoscaler.snapshot().get(model_name, {})
    events = controller.get("events", {})
    ups = sum(n for key, n in events.items()
              if key.startswith("up|"))
    downs = sum(n for key, n in events.items()
                if key.startswith("down|"))
    decisions = [r["decision"] for r
                 in core.flight.snapshot(model_name)
                 if r.get("reason") == "decision"]
    replica_seconds = (controller.get("replica_seconds", 0.0)
                       - seconds_t0)
    max_always = 4 * window_s

    arr = (np.array(latencies, dtype=float) / 1000.0
           if latencies else np.array([0.0]))
    result = {
        "fg_completed": len(latencies),
        "fg_errors": fg_errors[0],
        "fg_p50_us": round(float(np.percentile(arr, 50)), 1),
        "fg_p99_us": round(float(np.percentile(arr, 99)), 1),
        "slo_p99_us": slo_p99_us,
        "bulk": scenario.stats(),
        "peak_replicas": peak[0],
        "final_replicas": replica_set.count,
        "scale_ups": ups,
        "scale_downs": downs,
        "canary_rejects": replica_set.canary_rejects,
        "replica_seconds": round(replica_seconds, 2),
        "max_scale_always_seconds": round(max_always, 2),
        "replica_seconds_ratio": round(
            replica_seconds / max_always, 3) if max_always else 0.0,
        "kill_fired": kill["fired"],
        "kill_fg_errors": (fg_errors[0] - kill["errors_before"]
                           if kill["fired"] else None),
        "shed_state": controller.get("shed"),
        "flight_up_decisions": sum(
            1 for d in decisions if d.startswith("autoscale_up")),
        "flight_down_decisions": sum(
            1 for d in decisions if d.startswith("autoscale_down")),
        "window_s": round(window_s, 2),
    }
    return result


def run_tracing_measure(core, model_name: str = "add_sub_large",
                        threads: int = 4, requests: int = 120) -> dict:
    """Span-tracing overhead: the same closed loop run with tracing
    OFF and with trace_rate=1 (every request builds a full span tree,
    renders a compact record, and appends to the trace file). The
    stage's acceptance gate is overhead < 5% of throughput.

    Measured on ``add_sub_large`` (4 MiB tensors) — the ms-scale
    request shape latency attribution exists for (ROADMAP item 1's
    relay-fetch hunt), where the recorder's ~50-80 us per sampled
    request is noise. On a ~50 us toy request the same absolute cost
    is unavoidably a large fraction; that is what trace_rate
    sampling is for (at the Triton-default 1-in-1000 the amortized
    cost is well under 0.1 us/request even on `simple`)."""
    import tempfile as _tempfile
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    def request(seed: int):
        a = np.full((1048576,), float(seed % 1000), dtype=np.float32)
        b = np.arange(1048576, dtype=np.float32)
        t0 = InferInput("INPUT0", [1048576], "FP32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [1048576], "FP32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=model_name,
                                     inputs=[t0, t1], outputs=None)

    # Few distinct payloads: at 8 MiB of tensor data per request a
    # large pool would be memory, not load.
    pool_requests = [request(i) for i in range(8)]

    def closed_loop() -> tuple:
        latencies: list = []
        merge = _threading.Lock()
        per_thread = requests // threads

        def worker(offset: int):
            local = []
            for i in range(per_thread):
                req = pool_requests[(offset + i) % len(pool_requests)]
                t_start = time.monotonic_ns()
                core.infer(req)
                local.append(time.monotonic_ns() - t_start)
            with merge:
                latencies.extend(local)

        t0 = time.monotonic()
        pool = [_threading.Thread(target=worker, args=(i * 31,))
                for i in range(threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        elapsed = time.monotonic() - t0
        if not latencies or elapsed <= 0:
            return 0.0, 0.0
        latencies.sort()
        return (len(latencies) / elapsed,
                latencies[len(latencies) // 2] / 1000.0)

    # Warm the model (compile) outside both measurement windows.
    for req in pool_requests[:4]:
        core.infer(req)
    fd, trace_file = _tempfile.mkstemp(prefix="bench_trace_",
                                       suffix=".jsonl")
    os.close(fd)
    on_settings = {
        "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
        "trace_count": ["-1"], "log_frequency": ["100"],
        "trace_file": [trace_file], "trace_mode": ["compact"]}
    # Interleaved A/B rounds with medians: the recorder's absolute
    # cost is tens of us per request, far below this host's
    # minute-to-minute throughput drift — back-to-back single windows
    # would gate on machine noise, not tracing.
    off_rounds, on_rounds = [], []
    try:
        for _ in range(4):
            core.trace_setting("", {"trace_level": ["OFF"]})
            off_rounds.append(closed_loop())
            core.trace_setting("", on_settings)
            on_rounds.append(closed_loop())
    finally:
        core.trace_setting("", {"trace_level": ["OFF"]})
        try:
            with open(trace_file) as f:
                sampled = sum(1 for _ in f)
            os.unlink(trace_file)
        except OSError:
            sampled = 0
    off_rounds.sort()
    on_rounds.sort()
    off_tput, off_p50 = off_rounds[len(off_rounds) // 2]
    on_tput, on_p50 = on_rounds[len(on_rounds) // 2]
    overhead_pct = (100.0 * (off_tput - on_tput) / off_tput
                    if off_tput > 0 else 0.0)
    return {
        "trace_off_tput": round(off_tput, 2),
        "trace_off_p50_us": round(off_p50, 1),
        "trace_on_tput": round(on_tput, 2),
        "trace_on_p50_us": round(on_p50, 1),
        "trace_rate": 1,
        "sampled_records": sampled,
        "overhead_pct": round(overhead_pct, 2),
        "overhead_gate_pct": 5.0,
        "overhead_ok": overhead_pct < 5.0,
    }


def _overhead_ab_measure(core, toggle, prefix: str,
                         model_name: str = "add_sub_large",
                         threads: int = 4, requests: int = 120,
                         rounds: int = 8) -> dict:
    """Shared paired interleaved-A/B overhead driver for always-on
    per-request layers (telemetry histograms, flight capture): the
    identical closed loop on ``model_name`` with the layer disabled vs
    enabled, alternated per round so adjacent windows share the host's
    drift state. The first pair is a throwaway warm-up (its off-window
    absorbs allocator/cache ramp and reads biased), and the gate takes
    the true median over the remaining pairs — the upper-median of a
    handful of pairs is a 75th-percentile estimator that flips the
    gate on per-window scheduler noise. The median of PAIRED per-round
    ratios isolates the recording cost far more tightly than a ratio
    of medians at a 2%
    gate (the absolute cost is microseconds against a ~15 ms request).
    ``toggle`` is the object whose ``enabled`` attribute gates the
    layer; result keys are prefixed ``<prefix>_``."""
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request

    def request(seed: int):
        a = np.full((1048576,), float(seed % 1000), dtype=np.float32)
        b = np.arange(1048576, dtype=np.float32)
        t0 = InferInput("INPUT0", [1048576], "FP32")
        t0.set_data_from_numpy(a)
        t1 = InferInput("INPUT1", [1048576], "FP32")
        t1.set_data_from_numpy(b)
        return get_inference_request(model_name=model_name,
                                     inputs=[t0, t1], outputs=None)

    pool_requests = [request(i) for i in range(8)]

    def closed_loop() -> tuple:
        latencies: list = []
        merge = _threading.Lock()
        per_thread = requests // threads

        def worker(offset: int):
            local = []
            for i in range(per_thread):
                req = pool_requests[(offset + i) % len(pool_requests)]
                t_start = time.monotonic_ns()
                core.infer(req)
                local.append(time.monotonic_ns() - t_start)
            with merge:
                latencies.extend(local)

        t0 = time.monotonic()
        pool = [_threading.Thread(target=worker, args=(i * 31,))
                for i in range(threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        elapsed = time.monotonic() - t0
        if not latencies or elapsed <= 0:
            return 0.0, 0.0
        latencies.sort()
        return (len(latencies) / elapsed,
                latencies[len(latencies) // 2] / 1000.0)

    for req in pool_requests[:4]:
        core.infer(req)  # warm the model outside both windows
    was_enabled = toggle.enabled
    off_rounds, on_rounds, pair_overheads = [], [], []
    try:
        for index in range(rounds + 1):
            toggle.enabled = False
            off_tput_i, off_p50_i = closed_loop()
            toggle.enabled = True
            on_tput_i, on_p50_i = closed_loop()
            if index == 0:
                continue  # warm-up pair: ramp bias, not recording cost
            off_rounds.append((off_tput_i, off_p50_i))
            on_rounds.append((on_tput_i, on_p50_i))
            if off_tput_i > 0:
                pair_overheads.append(
                    100.0 * (off_tput_i - on_tput_i) / off_tput_i)
    finally:
        toggle.enabled = was_enabled
    off_rounds.sort()
    on_rounds.sort()
    off_tput, off_p50 = off_rounds[len(off_rounds) // 2]
    on_tput, on_p50 = on_rounds[len(on_rounds) // 2]
    pair_overheads.sort()
    if not pair_overheads:
        overhead_pct = 0.0
    elif len(pair_overheads) % 2:
        overhead_pct = pair_overheads[len(pair_overheads) // 2]
    else:
        mid = len(pair_overheads) // 2
        overhead_pct = (pair_overheads[mid - 1] + pair_overheads[mid]) / 2.0
    return {
        "%s_off_tput" % prefix: round(off_tput, 2),
        "%s_off_p50_us" % prefix: round(off_p50, 1),
        "%s_on_tput" % prefix: round(on_tput, 2),
        "%s_on_p50_us" % prefix: round(on_p50, 1),
        "pair_overheads_pct": [round(v, 2) for v in pair_overheads],
        "overhead_pct": round(overhead_pct, 2),
        "overhead_gate_pct": 2.0,
        "overhead_ok": overhead_pct < 2.0,
    }


def run_telemetry_measure(core, model_name: str = "add_sub_large",
                          threads: int = 4, requests: int = 120,
                          rounds: int = 8) -> dict:
    """Latency-histogram recording overhead: the identical closed loop
    with the telemetry registry disabled vs enabled (the always-on
    default). Each served request pays ~5 histogram observations
    (request + decode/queue/execute/encode) of a bisect + three
    counter updates under a per-histogram lock; the acceptance gate is
    <2% throughput cost — histograms must be cheap enough to NEVER
    turn off, because an SLO signal that gets disabled under load is
    not an SLO signal. (Shared driver: _overhead_ab_measure.)"""
    return _overhead_ab_measure(core, core.telemetry, "telemetry",
                                model_name=model_name, threads=threads,
                                requests=requests, rounds=rounds)


def run_flight_measure(core, model_name: str = "add_sub_large",
                       threads: int = 4, requests: int = 120,
                       rounds: int = 8) -> dict:
    """Flight-recorder capture overhead: the identical closed loop
    with the recorder disabled vs enabled (the always-on default).
    With capture on, EVERY request builds a scratch span tree
    (client_tpu.server.tracing.RequestTrace — ids from a seeded PRNG,
    boundary-chained clock reads) and pays one retroactive keep check
    at completion; nothing here is kept (clean traffic, generous
    threshold), so the cost measured is pure capture — the tax of
    having forensics armed. Gate: <2% throughput. (Shared driver:
    _overhead_ab_measure.)"""
    return _overhead_ab_measure(core, core.flight, "flight",
                                model_name=model_name, threads=threads,
                                requests=requests, rounds=rounds)


def run_fetch_measure(core, threads: int = 4, rounds: int = 3,
                      per_round: int = 3) -> dict:
    """Relay-fetch A/B (ROADMAP item 1's measured form): interleaved
    closed loops on the ``fetch_bench`` / ``fetch_bench_legacy`` pair
    — identical 4-output x 4 MiB models, one with the overlapped
    output-fetch subsystem (client_tpu.server.fetch), one opted out to
    the legacy serial blocking np.asarray. Reports client
    throughput/p50 per arm plus the server-side
    ``tpu_stage_duration_us{stage=relay_fetch}`` p50 window deltas and
    their ratio — on an accelerator this is the device->host relay
    win itself; on the cpu backend both arms materialize committed
    host buffers and the ratio sits near 1 (tools/fetch_smoke.py
    gates the overlap mechanism with simulated transfers)."""
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )

    def request(model_name: str, seed: int):
        tensor = InferInput("INPUT0", [1, 16], "FP32")
        tensor.set_data_from_numpy(
            np.full((1, 16), float(seed % 31), dtype=np.float32))
        return get_inference_request(model_name=model_name,
                                     inputs=[tensor], outputs=None)

    def closed_loop(model_name: str) -> tuple:
        latencies: list = []
        merge = _threading.Lock()

        def worker(offset: int):
            local = []
            for i in range(per_round):
                req = request(model_name, offset * 31 + i)
                t_start = time.monotonic_ns()
                core.infer(req)
                local.append(time.monotonic_ns() - t_start)
            with merge:
                latencies.extend(local)

        t0 = time.monotonic()
        pool = [_threading.Thread(target=worker, args=(i,))
                for i in range(threads)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        elapsed = time.monotonic() - t0
        if not latencies or elapsed <= 0:
            return 0.0, 0.0
        latencies.sort()
        return (len(latencies) / elapsed,
                latencies[len(latencies) // 2] / 1000.0)

    for model_name in ("fetch_bench", "fetch_bench_legacy"):
        closed_loop(model_name)  # warm (compile + first fused batch)
    before = core.metrics_text()
    over_rounds, legacy_rounds = [], []
    for _ in range(rounds):
        # Interleaved windows: adjacent A/B rounds share the host's
        # drift state (same discipline as run_telemetry_measure).
        over_rounds.append(closed_loop("fetch_bench"))
        legacy_rounds.append(closed_loop("fetch_bench_legacy"))
    after = core.metrics_text()
    over_rounds.sort()
    legacy_rounds.sort()
    over_tput, over_p50 = over_rounds[len(over_rounds) // 2]
    legacy_tput, legacy_p50 = legacy_rounds[len(legacy_rounds) // 2]
    quantiles = histogram_quantiles(summarize_metrics(
        [parse_prometheus(before), parse_prometheus(after)]))
    over_entry = quantiles.get("stage_duration_us|fetch_bench|srelay_fetch")
    legacy_entry = quantiles.get(
        "stage_duration_us|fetch_bench_legacy|srelay_fetch")
    over_relay = over_entry["p50_us"] if over_entry else 0.0
    legacy_relay = legacy_entry["p50_us"] if legacy_entry else 0.0
    return {
        "overlapped_tput": round(over_tput, 2),
        "overlapped_p50_us": round(over_p50, 1),
        "legacy_tput": round(legacy_tput, 2),
        "legacy_p50_us": round(legacy_p50, 1),
        "relay_fetch_p50_overlapped_us": round(over_relay, 1),
        "relay_fetch_p50_legacy_us": round(legacy_relay, 1),
        "relay_fetch_p50_speedup": round(
            legacy_relay / over_relay, 2) if over_relay > 0 else 0.0,
        "relay_fetch_executions": int(
            over_entry["count"] if over_entry else 0),
    }


def sequence_stats(core, model_name: str):
    """Sequence-scheduler snapshot for bench evidence (slot occupancy
    + lifetime counters from ModelStatistics.sequence_stats)."""
    try:
        stats = core.model_statistics(model_name)
        seq = stats.model_stats[0].sequence_stats
        return {
            "active_sequences": int(seq.active_sequences),
            "slot_total": int(seq.slot_total),
            "backlog_depth": int(seq.backlog_depth),
            "sequences_started": int(seq.sequences_started),
            "sequences_completed": int(seq.sequences_completed),
            "step_count": int(seq.step_count),
            "fused_steps": int(seq.fused_steps),
            "idle_reclaimed_total": int(seq.idle_reclaimed_total),
        }
    except Exception:  # noqa: BLE001 — evidence, never a failure
        return None


class PipelineSampler:
    """Polls the batcher gauges WHILE a measured run is live: pending
    depth and in-flight count are point-in-time values, so reading
    them after the harness's closed-loop clients drain would always
    record the idle 0 — the max under load is the evidence."""

    def __init__(self, core, names, interval_s: float = 0.5):
        import threading

        self._core = core
        self._names = list(names)
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self.max_pending: dict = {}
        self.max_inflight: dict = {}

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)
        return False

    def reset(self) -> None:
        self.max_pending.clear()
        self.max_inflight.clear()

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            for name in self._names:
                snap = fusion_stats(self._core, name)
                if snap is None:
                    continue
                self.max_pending[name] = max(
                    self.max_pending.get(name, 0), snap["pending_count"])
                self.max_inflight[name] = max(
                    self.max_inflight.get(name, 0), snap["inflight_count"])


# Continuous-batching A/B config (tools/llm_smoke.py shares it): an
# attention-dominated model with a LONG configured context, because
# that is the dense arm's honest cost — a dense lane reserves (and
# attends over) max_seq every step regardless of actual sequence
# length, which is exactly why decode_lanes was capped at 4. The paged
# arm's block tables bucket attention to the longest LIVE sequence.
LLM_CONTINUOUS_CFG = dict(d_model=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, d_ff=128, max_seq=8192)
LLM_CONTINUOUS_SYS = ("System: you are a terse benchmark assistant. "
                      "Answer briefly. ")
LLM_CONTINUOUS_MAX_TOKENS = 48


def _llm_closed_loop(model, concurrency: int, n_requests: int,
                     max_tokens: int = LLM_CONTINUOUS_MAX_TOKENS) -> dict:
    """Closed-loop generate driver against the model's scheduler
    (client-observed TTFT/ITL; every request carries the shared system
    prompt so the paged arm's prefix cache is exercised)."""
    import numpy as np

    lock = threading.Lock()
    ttfts: list = []
    gaps: list = []
    tokens = [0]
    work = list(range(n_requests))

    def worker():
        while True:
            with lock:
                if not work:
                    return
                i = work.pop()
            prompt = (LLM_CONTINUOUS_SYS
                      + "Question %d about topic %d?" % (i, i * 7))
            t0 = time.monotonic()
            last = t0
            got = 0
            for _ in model._generate(
                    {"text_input": np.array([prompt.encode()],
                                            dtype=np.object_),
                     "max_tokens": np.array([max_tokens],
                                            dtype=np.int32),
                     "ignore_eos": np.array([True])}, {}):
                now = time.monotonic()
                with lock:
                    if got == 0:
                        ttfts.append(now - t0)
                    else:
                        gaps.append(now - last)
                last = now
                got += 1
            with lock:
                tokens[0] += got

    t_start = time.monotonic()
    threads = [threading.Thread(target=worker)
               for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start

    def pct(values, q):
        ordered = sorted(values)
        if not ordered:
            return 0.0
        return ordered[min(int(len(ordered) * q), len(ordered) - 1)]

    return {
        "tokens_per_sec": round(tokens[0] / wall, 1) if wall else 0.0,
        "ttft_p50_ms": round(pct(ttfts, 0.50) * 1e3, 2),
        "ttft_p99_ms": round(pct(ttfts, 0.99) * 1e3, 2),
        "itl_p50_ms": round(pct(gaps, 0.50) * 1e3, 3),
        "itl_p99_ms": round(pct(gaps, 0.99) * 1e3, 2),
        "wall_s": round(wall, 2),
    }


def _llm_token_parity(dense, paged, max_tokens: int = 12) -> bool:
    """Greedy paged decode must be token-exact vs the dense arm —
    across the batched short-prompt prefill, the chunked long-prompt
    prefill, and a prefix-cache-hit prompt."""
    import numpy as np

    prompts = [
        b"short parity prompt",
        (LLM_CONTINUOUS_SYS + "chunked prefill parity check " * 4
         ).encode(),
        (LLM_CONTINUOUS_SYS + "prefix hit parity tail").encode(),
    ]

    def run(model, prompt):
        return [t for t in model._generate(
            {"text_input": np.array([prompt], dtype=np.object_),
             "max_tokens": np.array([max_tokens], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})]

    return all(run(dense, p) == run(paged, p) for p in prompts)


def _llm_chaos_pass(paged) -> bool:
    """Cancel mid-stream + one forced crash-recovery: the page pool
    must come back leak-free (the acceptance gate's cancel/crash
    arm). Returns True when a post-crash request completes."""
    import numpy as np

    from client_tpu.utils import InferenceServerException

    def start(prompt, max_tokens):
        return paged._generate(
            {"text_input": np.array([prompt], dtype=np.object_),
             "max_tokens": np.array([max_tokens], dtype=np.int32),
             "ignore_eos": np.array([True])}, {})

    gen = start(b"cancelled mid-stream request", 40)
    next(gen)
    gen.close()

    real = paged._paged_decode
    state = {"armed": True}

    def exploding(*args, **kwargs):
        if state["armed"]:
            state["armed"] = False
            raise RuntimeError("injected device failure")
        return real(*args, **kwargs)

    paged._paged_decode = exploding
    try:
        list(start(b"crash victim", 16))
    except InferenceServerException:
        pass
    finally:
        paged._paged_decode = real
    try:
        return len(list(start(b"post crash recovery", 4))) == 4
    except InferenceServerException:
        return False


def _llm_pool_drained(paged, timeout_s: float = 30.0) -> dict:
    """Waits for in-flight chunks to deliver, then snapshots the pool
    (leak gate: pages_used and pages_reserved must be 0)."""
    deadline = time.monotonic() + timeout_s
    snap = paged.kv_stats()
    while time.monotonic() < deadline and (
            snap["pages_used"] or snap["pages_reserved"]):
        time.sleep(0.05)
        snap = paged.kv_stats()
    return snap


def run_llm_continuous_measure(concurrencies=(4, 16),
                               paged_lanes: int = 0,
                               requests_per_worker: int = 4,
                               chaos: bool = True) -> dict:
    """Paged-KV continuous-batching A/B (ROADMAP item 2's measured
    form): a dense-arm c4 baseline (`paged_kv=False`, 4 lanes — the
    pre-paged ceiling) against the paged arm at each concurrency in
    ``concurrencies``. Both arms run the same closed-loop workload
    with a shared system prompt. Reports tokens/s + client TTFT/ITL
    per arm, paged pool peak/prefix-hit accounting, token parity, and
    the post-chaos leak check."""
    from client_tpu.models.llm import LlmConfig, LlmModel

    cfg = LlmConfig(**LLM_CONTINUOUS_CFG)
    lanes = paged_lanes or max(concurrencies)
    pages_per_seq_live = 8  # ~ (prompt + max_tokens) / page_size
    dense = LlmModel(name="llm_dense_ab", cfg=cfg, paged_kv=False,
                     decode_lanes=4)
    dense.warmup()
    paged = LlmModel(name="llm_paged_ab", cfg=cfg, paged_kv=True,
                     decode_lanes=lanes, page_size=16,
                     kv_pages=max(lanes * pages_per_seq_live, 64))
    paged.warmup()

    out: dict = {
        "max_tokens": LLM_CONTINUOUS_MAX_TOKENS,
        "paged_lanes": lanes,
        "kv_pages": paged._num_pages,
        "dense_equivalent_pages": 4 * paged._pages_per_seq,
        "token_parity": _llm_token_parity(dense, paged),
    }
    # Warm pass per arm: every (compact batch, table width) XLA bucket
    # the measured pass will touch compiles here, not mid-measurement.
    _llm_closed_loop(dense, 4, 8)
    _llm_closed_loop(paged, max(concurrencies), 2 * max(concurrencies))

    base = _llm_closed_loop(dense, 4, 4 * requests_per_worker)
    out["dense_c4"] = base
    for conc in concurrencies:
        run = _llm_closed_loop(paged, conc,
                               conc * requests_per_worker)
        snap = paged.kv_stats()
        run["pages_used_peak"] = snap["pages_used_peak"]
        run["prefix_hits_total"] = snap["prefix_hits_total"]
        out["paged_c%d" % conc] = run
        if base["tokens_per_sec"]:
            run["speedup_vs_dense_c4"] = round(
                run["tokens_per_sec"] / base["tokens_per_sec"], 2)
        if base["itl_p99_ms"]:
            run["itl_p99_vs_dense_c4"] = round(
                run["itl_p99_ms"] / base["itl_p99_ms"], 2)
    if chaos:
        out["chaos_recovered"] = _llm_chaos_pass(paged)
    final = _llm_pool_drained(paged)
    out["pages_used_final"] = final["pages_used"]
    out["pages_reserved_final"] = final["pages_reserved"]
    out["prefill_chunks_total"] = final["prefill_chunks_total"]
    dense.unload()
    paged.unload()
    return out


def run_ensemble_dataflow_measure(core=None, concurrency: int = 16,
                                  rounds: int = 3, per_round: int = 4,
                                  hot_set: int = 4) -> dict:
    """Device-resident ensemble dataflow A/B (ROADMAP item 1's
    ensemble form): interleaved closed loops on the ``ensemble_ab`` /
    ``ensemble_ab_legacy`` pair — identical three-step graphs whose
    backbone wall cost scales with batch ROWS (so ensemble-level
    gather cannot amortize it away), one executed as a device-resident
    dataflow graph (per-stage batching + composing-cache
    short-circuit), one through the legacy host-mediated step loop
    with prod-style ensemble-level dynamic batching. Two phases:
    distinct inputs at ``concurrency`` measure the backbone fusion
    ratio (execution_count / inference_count deltas — per-stage
    batching across concurrent dataflow requests); a pinned hot set
    measures steady-state throughput where the dataflow arm's stage
    cache short-circuits the subgraph (the retired PR-5 caveat,
    measured). Also asserts byte-level golden parity across arms and
    sends one traced request through the dataflow arm for the span
    gate: ensemble_step spans present, ZERO relay_fetch spans — the
    no-host-round-trip evidence."""
    import json as _json
    import os as _os
    import tempfile as _tempfile
    import threading as _threading

    import numpy as np

    from client_tpu._infer_common import InferInput
    from client_tpu.grpc._utils import get_inference_request
    from client_tpu.perf.metrics_manager import parse_prometheus

    own_core = core is None
    if own_core:
        from client_tpu.server.app import build_core

        core = build_core(["ensemble_ab", "ensemble_ab_legacy"])

    def request(model_name: str, seed: int):
        tensor = InferInput("RAW", [1, 8], "FP32")
        tensor.set_data_from_numpy(
            ((np.arange(8, dtype=np.float32) + 1.0)
             * np.float32(seed % 99991 + 1)).reshape(1, 8))
        return get_inference_request(model_name=model_name,
                                     inputs=[tensor], outputs=None)

    seq = [0]
    seq_lock = _threading.Lock()

    def next_seed() -> int:
        # Fresh seeds are cache misses by construction; the hot phase
        # pins its working set instead.
        with seq_lock:
            seq[0] += 1
            return seq[0]

    def closed_loop(model_name: str, seeds=None) -> tuple:
        latencies: list = []
        merge = _threading.Lock()

        def worker(offset: int):
            local = []
            for i in range(per_round):
                if seeds is None:
                    seed = next_seed()
                else:
                    seed = seeds[(offset * per_round + i) % len(seeds)]
                req = request(model_name, seed)
                t_start = time.monotonic_ns()
                core.infer(req)
                local.append(time.monotonic_ns() - t_start)
            with merge:
                latencies.extend(local)

        t0 = time.monotonic()
        pool = [_threading.Thread(target=worker, args=(i,))
                for i in range(concurrency)]
        for thread in pool:
            thread.start()
        for thread in pool:
            thread.join()
        elapsed = time.monotonic() - t0
        latencies.sort()
        return (len(latencies) / elapsed if elapsed > 0 else 0.0,
                latencies[len(latencies) // 2] / 1000.0
                if latencies else 0.0)

    def counts(model_name: str) -> tuple:
        stats = core.model_statistics(model_name)
        s = stats.model_stats[0]
        return int(s.inference_count), int(s.execution_count)

    try:
        # Warm both arms: batcher gather threads spin up, composing
        # models load, every shape bucket the measurement touches runs
        # once outside the window.
        closed_loop("ensemble_ab")
        closed_loop("ensemble_ab_legacy")

        # Golden parity, cold inputs: the same RAW tensor through both
        # arms must produce byte-identical SCORE bytes.
        parity = True
        for _ in range(3):
            seed = next_seed()
            blobs = [
                bytes(core.infer(request(name, seed))
                      .raw_output_contents[0])
                for name in ("ensemble_ab", "ensemble_ab_legacy")]
            parity = parity and blobs[0] == blobs[1]

        # Phase 1 — distinct inputs at full concurrency: the backbone
        # fusion ratio is the per-stage batching evidence (1.0 would
        # mean every dataflow request executed its backbone alone).
        inf0, exec0 = counts("ab_backbone")
        distinct_before = core.metrics_text()
        fusion_rounds = [closed_loop("ensemble_ab")
                         for _ in range(rounds)]
        distinct_after = core.metrics_text()
        inf1, exec1 = counts("ab_backbone")
        d_inf, d_exec = inf1 - inf0, exec1 - exec0
        fusion_ratio = round(d_exec / d_inf, 4) if d_inf else 1.0
        fusion_rounds.sort()
        distinct_tput, distinct_p50 = \
            fusion_rounds[len(fusion_rounds) // 2]

        # Phase 2 — pinned hot set, interleaved A/B windows: the
        # dataflow arm's stage cache short-circuits the subgraph; the
        # legacy arm re-pays the row-proportional backbone each cycle.
        hot = [next_seed() for _ in range(hot_set)]
        for seed in hot:  # populate the stage cache (async inserts)
            core.infer(request("ensemble_ab", seed))
        time.sleep(0.3)
        before = core.metrics_text()
        dataflow_rounds, legacy_rounds = [], []
        for _ in range(rounds):
            dataflow_rounds.append(closed_loop("ensemble_ab", seeds=hot))
            legacy_rounds.append(
                closed_loop("ensemble_ab_legacy", seeds=hot))
        after = core.metrics_text()
        dataflow_rounds.sort()
        legacy_rounds.sort()
        dataflow_tput, dataflow_p50 = \
            dataflow_rounds[len(dataflow_rounds) // 2]
        legacy_tput, legacy_p50 = legacy_rounds[len(legacy_rounds) // 2]
        def delta(before_text: str, after_text: str, attr: str) -> int:
            m0 = parse_prometheus(before_text)
            m1 = parse_prometheus(after_text)
            return int(getattr(m1, attr).get("ensemble_ab", 0.0)
                       - getattr(m0, attr).get("ensemble_ab", 0.0))

        # Span gate: one traced request through the dataflow arm. The
        # record must hold the per-stage ensemble_step chain and ZERO
        # relay_fetch spans — interior tensors never detoured through
        # a host fetch.
        fd, trace_file = _tempfile.mkstemp(prefix="bench_ens_trace_",
                                           suffix=".jsonl")
        _os.close(fd)
        step_spans = relay_spans = 0
        try:
            core.trace_setting("ensemble_ab", {
                "trace_level": ["TIMESTAMPS"], "trace_rate": ["1"],
                "trace_count": ["-1"], "log_frequency": ["1"],
                "trace_file": [trace_file], "trace_mode": ["compact"]})
            core.infer(request("ensemble_ab", next_seed()))
            core.trace_setting("ensemble_ab", {
                key: [] for key in ("trace_level", "trace_rate",
                                    "trace_count", "log_frequency",
                                    "trace_file", "trace_mode")})
            with open(trace_file) as f:
                for line in f:
                    if not line.strip():
                        continue
                    names = [s["name"]
                             for s in _json.loads(line)["spans"]]
                    step_spans += names.count("ensemble_step")
                    relay_spans += names.count("relay_fetch")
        finally:
            try:
                _os.unlink(trace_file)
            except OSError:
                pass
    finally:
        if own_core:
            core.shutdown()

    return {
        "concurrency": concurrency,
        "golden_parity": parity,
        "backbone_inferences": d_inf,
        "backbone_executions": d_exec,
        "fusion_ratio": fusion_ratio,
        "distinct_tput": round(distinct_tput, 2),
        "distinct_p50_us": round(distinct_p50, 1),
        "dataflow_tput": round(dataflow_tput, 2),
        "dataflow_p50_us": round(dataflow_p50, 1),
        "legacy_tput": round(legacy_tput, 2),
        "legacy_p50_us": round(legacy_p50, 1),
        "speedup": round(dataflow_tput / legacy_tput, 2)
        if legacy_tput else 0.0,
        # Fusion counts accrue where batcher dispatches happen (the
        # distinct phase); cache hits where the hot set repeats.
        "ensemble_fused": delta(distinct_before, distinct_after,
                                "ensemble_fused_total"),
        "ensemble_cache_hits": delta(before, after,
                                     "ensemble_cache_hits_total"),
        "ensemble_step_spans": step_spans,
        "interior_relay_fetch_spans": relay_spans,
    }


def run_python_harness(model: str, batch: int, concurrency: int,
                       shared_memory: str, output_shm: int,
                       core=None, address: str = "",
                       warm_s: float = 3.0,
                       sequence_length: int = 0) -> tuple[float, float]:
    """Python harness measurement; in-process when ``core`` is given,
    gRPC otherwise; (throughput, p50_us). ``sequence_length`` > 0
    drives sequence load (each context runs whole sequences through
    the server's sequence scheduler)."""
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import (
        ConcurrencyManager,
        InferDataManager,
        SequenceManager,
    )
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig

    if core is not None:
        factory = ClientBackendFactory(BackendKind.IN_PROCESS, core=core)
    else:
        factory = ClientBackendFactory(BackendKind.TRITON_GRPC, url=address)
    setup_backend = factory.create()
    parsed = ModelParser().parse(setup_backend, model, batch_size=batch)
    loader = DataLoader(parsed)
    loader.generate_data()
    kwargs = {}
    if shared_memory == "tpu":
        kwargs = dict(shared_memory="tpu", output_shm_size=output_shm,
                      tpu_arena_url=address)
    data_manager = InferDataManager(parsed, loader, batch_size=batch,
                                    **kwargs)
    sequence_manager = None
    if sequence_length > 0:
        sequence_manager = SequenceManager(
            sequence_length=sequence_length,
            sequence_length_variation=0.0)
    manager = ConcurrencyManager(
        factory=factory, model=parsed, data_loader=loader,
        data_manager=data_manager, async_mode=True, max_threads=8,
        sequence_manager=sequence_manager,
    )
    manager.init()
    config = MeasurementConfig(measurement_interval_ms=2000, max_trials=4,
                               stability_threshold=0.2, batch_size=batch)
    profiler = InferenceProfiler(
        manager, config, setup_backend, model,
        composing_models=parsed.composing_models)
    manager.change_concurrency_level(1)
    time.sleep(warm_s)  # warm the compiled path before measuring
    results = profiler.profile_concurrency_range(concurrency, concurrency)
    manager.cleanup()
    setup_backend.close()
    status = results[-1]
    return status.throughput, status.latency_percentiles.get(50, 0.0)


def run_fleet_measure(concurrency: int = 8, hedge_max_ratio: float = 0.05,
                      spike_ms: float = 0.0, kill_after_s: float = 0.0,
                      window_ms: int = 2500, trials: int = 2):
    """Spin a 2-server in-process fleet (gRPC, `simple`), measure one
    concurrency level through the EndpointPool client, optionally
    latency-spiking or killing one endpoint mid-run. Returns
    (PerfStatus, pool_stats). Self-contained: servers and pool are
    torn down before returning."""
    from client_tpu import robust
    from client_tpu.perf.client_backend import (
        BackendKind,
        ClientBackendFactory,
    )
    from client_tpu.perf.data_loader import DataLoader
    from client_tpu.perf.load_manager import (
        ConcurrencyManager,
        InferDataManager,
    )
    from client_tpu.perf.model_parser import ModelParser
    from client_tpu.perf.profiler import InferenceProfiler, MeasurementConfig
    from client_tpu.server import chaos
    from client_tpu.server.app import build_core, start_grpc_server

    fleet = []
    for i in range(2):
        fleet_core = build_core(["simple"])
        fleet_core.chaos_scope = "bench_ep%d" % i
        fleet.append((fleet_core, start_grpc_server(core=fleet_core)))
    pool = robust.EndpointPool(
        [h.address for _c, h in fleet],
        hedge_delay_min_ms=2.0, hedge_max_ratio=hedge_max_ratio)
    factory = ClientBackendFactory(
        BackendKind.TRITON_GRPC, url=",".join(pool.urls),
        retry_policy=robust.RetryPolicy(max_attempts=4,
                                        initial_backoff_s=0.01),
        endpoint_pool=pool)
    scenario_timer = None
    try:
        setup_backend = factory.create()
        parsed = ModelParser().parse(setup_backend, "simple", batch_size=1)
        loader = DataLoader(parsed)
        loader.generate_data()
        manager = ConcurrencyManager(
            factory=factory, model=parsed, data_loader=loader,
            data_manager=InferDataManager(parsed, loader, batch_size=1),
            async_mode=True, max_threads=8)
        manager.init()
        if spike_ms > 0:
            chaos.configure_scope("bench_ep0",
                                  chaos.ChaosConfig(latency_ms=spike_ms))
        if kill_after_s > 0:
            scenario_timer = threading.Timer(
                kill_after_s, fleet[0][1].stop)
            scenario_timer.daemon = True
            scenario_timer.start()
        profiler = InferenceProfiler(
            manager,
            MeasurementConfig(measurement_interval_ms=window_ms,
                              max_trials=trials, stability_threshold=0.5,
                              batch_size=1),
            setup_backend, "simple")
        manager.change_concurrency_level(2)
        time.sleep(0.8)  # warm the fleet + latency window
        results = profiler.profile_concurrency_range(concurrency,
                                                     concurrency)
        manager.cleanup()
        setup_backend.close()
        return results[-1], pool.stats()
    finally:
        if scenario_timer is not None:
            scenario_timer.cancel()
        chaos.configure_scope("bench_ep0", None)
        pool.close()
        for fleet_core, handle in fleet:
            try:
                handle.stop()
            except Exception:  # already killed mid-run
                pass


def main() -> None:
    global _OUT_PATH
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--init-marker", required=True)
    ap.add_argument("--deadline-ts", type=float, required=True,
                    help="absolute unix time to be fully done by")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (e.g. cpu); default = image")
    ap.add_argument("--skip-stages", default="",
                    help="comma-separated stage names already measured "
                         "elsewhere (the orchestrator's CPU supplement "
                         "only re-measures what is missing)")
    args = ap.parse_args()
    _OUT_PATH = pathlib.Path(args.out)

    skip_stages = set(filter(None, args.skip_stages.split(",")))

    def stage_wanted(name: str) -> bool:
        if name in skip_stages:
            log("%s skipped (already measured by the orchestrator)" % name)
            return False
        return True

    def remaining() -> float:
        return args.deadline_ts - time.time()

    def on_sigint(sig, frame):
        log("SIGINT — flushing partial results")
        flush_result()
        os._exit(0)

    signal.signal(signal.SIGINT, on_sigint)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    cache_dir = REPO / ".jax_cache"
    cache_dir.mkdir(exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", str(cache_dir))

    log("importing jax (platform=%s)..." % (args.platform or "default"))
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    devices = jax.devices()
    platform = devices[0].platform
    RESULT["platform"] = platform
    log("jax ready: %d x %s" % (len(devices), platform))

    sys.path.insert(0, str(REPO))
    from client_tpu.server.app import build_core, start_grpc_server

    log("building core + warming 'simple'...")
    core = build_core(["simple"])
    # Device sampling is process-global (devstats singleton), so one
    # arm covers every core the stages build later (fleets included).
    set_device_stats(core.devstats)
    handle = start_grpc_server(core=core)
    log("gRPC server on %s" % handle.address)
    pathlib.Path(args.init_marker).write_text(
        json.dumps({"address": handle.address, "platform": platform}))
    RESULT["address"] = handle.address
    flush_result()

    # Pre-flight device round trip, watchdogged. Runs AFTER the init
    # marker is written (the orchestrator's init deadline must never
    # ride on a wedged relay) and clamped to the budget. When the
    # relay is wedged (observed failure mode: every device op blocks
    # forever), the host-placed `simple` stages still measure fine —
    # this records WHY the model-bound stages are absent.
    def _device_probe():
        import numpy as _np

        x = jax.device_put(_np.ones((8, 8), _np.float32))
        return float(_np.asarray((x * 2).sum()))

    try:
        run_with_watchdog("device probe", _device_probe,
                          min(90.0, max(20.0, remaining() - 60)))
        RESULT["device_probe"] = "ok"
    except RuntimeError as exc:
        if "stalled" in str(exc):
            RESULT["device_probe"] = "stalled: %s" % exc
            log("device probe stalled — model-bound stages will be "
                "skipped while the relay is wedged")
        else:
            RESULT["device_probe"] = "error: %s" % exc
    except Exception as exc:  # noqa: BLE001 — a real device error
        RESULT["device_probe"] = "error: %s" % exc
    flush_result()

    binary = native_binary()
    RESULT["harness"] = "native" if binary else "python"

    # Stage 2: simple over gRPC — the guaranteed number.
    if stage_wanted("simple_grpc"):
      try:
          if binary:
              tput, p50 = run_native(binary, handle.address, "simple",
                                     batch=1, concurrency=4,
                                     shared_memory="none", output_shm=0,
                                     timeout=max(30.0, min(180.0, remaining())))
          else:
              tput, p50 = run_python_harness("simple", 1, 4, "none", 0,
                                             address=handle.address)
          record_stage("simple_grpc", tput, p50,
                       {"vs_baseline": round(tput / BASELINE_SIMPLE, 4)})
      except Exception as exc:  # noqa: BLE001 — always degrade, never die
        log("simple_grpc failed: %s" % exc)

    # Stage 3: simple in-process (RPC tax datum).
    if remaining() > 60 and stage_wanted("simple_inprocess"):
        try:
            tput, p50 = run_python_harness("simple", 1, 4, "none", 0,
                                           core=core, warm_s=1.0)
            record_stage(
                "simple_inprocess", tput, p50,
                {"vs_baseline": round(tput / BASELINE_INPROCESS, 4),
                 "baseline_src": "ref triton_c_api in-process row"})
        except Exception as exc:  # noqa: BLE001
            log("simple_inprocess failed: %s" % exc)

    # Stage 2b: simple against tpu_serverd — the C++ gRPC front-end
    # (native/server/) embedding the same core. `simple` is
    # host-placed, so the daemon runs on the CPU platform and never
    # contends for the TPU the live in-child server holds.
    serverd = REPO / "native" / "build" / "tpu_serverd"
    want_native_grpc = "simple_grpc_native_server" not in skip_stages
    want_native_http = "simple_http_native_server_c1" not in skip_stages
    if binary and serverd.exists() and remaining() > 60 \
            and (want_native_grpc or want_native_http):
        daemon = None
        http_line = None
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="")
            # New session so an orchestrator kill of this child can't
            # orphan the daemon mid-init (we kill its whole group).
            daemon = subprocess.Popen(
                [str(serverd), "--port", "0", "--http-port", "0",
                 "--models", "simple"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, cwd=str(REPO), env=env,
                start_new_session=True)
            import select

            init_by = time.time() + min(120.0, max(30.0, remaining() - 30))
            line = ""
            while time.time() < init_by:
                ready, _, _ = select.select([daemon.stdout], [], [], 1.0)
                if ready:
                    line = daemon.stdout.readline().strip()
                    break
                if daemon.poll() is not None:
                    break
            if not line.startswith("LISTENING "):
                raise RuntimeError("tpu_serverd init: %r" % line)
            address = "127.0.0.1:%s" % line.split()[1]
            http_line = daemon.stdout.readline().strip()
            if want_native_grpc:
                tput, p50 = run_native(
                    binary, address, "simple", batch=1, concurrency=4,
                    shared_memory="none", output_shm=0,
                    timeout=max(30.0, min(180.0, remaining())))
                record_stage("simple_grpc_native_server", tput, p50,
                             {"vs_baseline": round(tput / BASELINE_SIMPLE,
                                                   4)})
        except Exception as exc:  # noqa: BLE001
            log("simple_grpc_native_server failed: %s" % exc)
        # HTTP front-end at concurrency 1: the same shape as the
        # reference's published 1407.84 infer/s quick-start row
        # (HTTP, concurrency 1) — a direct apples-to-apples datum.
        try:
            if daemon is not None and http_line is not None and \
                    http_line.startswith("LISTENING-HTTP ") and \
                    want_native_http and remaining() > 30:
                http_address = "127.0.0.1:%s" % http_line.split()[1]
                tput, p50 = run_native(
                    binary, http_address, "simple", batch=1, concurrency=1,
                    shared_memory="none", output_shm=0, protocol="http",
                    timeout=max(30.0, min(180.0, remaining())))
                record_stage(
                    "simple_http_native_server_c1", tput, p50,
                    {"vs_baseline": round(tput / BASELINE_SIMPLE, 4)})
        except Exception as exc:  # noqa: BLE001
            log("simple_http_native_server_c1 failed: %s" % exc)
        finally:
            if daemon is not None:
                import signal as _signal

                try:
                    os.killpg(daemon.pid, _signal.SIGTERM)
                except OSError:
                    daemon.terminate()
                try:
                    daemon.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    try:
                        os.killpg(daemon.pid, _signal.SIGKILL)
                    except OSError:
                        daemon.kill()

    # Stage 3b: simple through the NATIVE in-process backend — the
    # C++ harness embedding the server core, no server process at all
    # (triton_c_api analogue). Subprocess so its embedded interpreter
    # doesn't fight this one; CPU platform because `simple` is
    # host-placed anyway and the TPU belongs to the live server here.
    if binary and remaining() > 60 \
            and stage_wanted("simple_inprocess_native"):
        try:
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PALLAS_AXON_POOL_IPS="")
            csv = "/tmp/bench_inproc_latency.csv"
            proc = subprocess.run(
                [str(binary), "-m", "simple",
                 "--service-kind", "in_process", "-b", "1",
                 "--concurrency-range", "4", "--async",
                 "-p", "2000", "-r", "4", "-s", "20",
                 "--max-threads", "8", "-f", csv],
                capture_output=True, text=True, cwd=str(REPO), env=env,
                timeout=max(30.0, min(180.0, remaining())))
            if proc.returncode == 0:
                with open(csv) as f:
                    f.readline()
                    row = f.readline().strip().split(",")
                record_stage(
                    "simple_inprocess_native", float(row[1]), float(row[2]),
                    {"vs_baseline": round(
                        float(row[1]) / BASELINE_INPROCESS, 4),
                     "baseline_src": "ref triton_c_api in-process row"})
            else:
                log("native in_process failed rc=%d: %s"
                    % (proc.returncode, proc.stderr[-300:]))
        except Exception as exc:  # noqa: BLE001
            log("simple_inprocess_native failed: %s" % exc)

    # Stage 4: resnet50 with TPU shared memory — the headline.
    resnet_budget = 300 if platform != "cpu" else 150
    exec_extra: dict = {}
    if remaining() > resnet_budget and not relay_blocked() \
            and stage_wanted("resnet50_tpu_shm_grpc"):
        try:
            log("warming resnet50 (batch 8)...")
            run_with_watchdog(
                "resnet50 warmup",
                lambda: core.repository.load("resnet50").warmup(),
                min(240.0, max(120.0, remaining() - 60)))
            # Pure-model cost (dispatch + fresh host fetch), so served
            # p50 splits into model time vs serving overhead. On this
            # image the axon relay's device->host hop is the floor.
            # Probe errors never kill the stage; a PERSISTENT relay
            # stall does (measuring against a wedged device would be
            # fiction) via the relay_blocked() gate below.
            exec_ms = None
            try:
                exec_ms = run_with_watchdog(
                    "exec probe",
                    lambda: measure_model_exec_ms(core, "resnet50", batch=8),
                    150.0)
                exec_extra = {"model_exec_ms": round(exec_ms, 2)}
                log("resnet50 bare exec+fetch (batch 8): %.1f ms" % exec_ms)
            except Exception as exc:  # noqa: BLE001
                log("exec probe failed (continuing): %s" % exc)
            try:
                if relay_blocked():
                    raise RuntimeError("relay wedged — probe skipped")
                # Relay-corrected device step time (chained dispatches,
                # one fetch): the honest device-side number the raw
                # probe hides behind the ~65 ms fetch tax.
                dev_ms, fetch_ms = run_with_watchdog(
                    "corrected exec probe",
                    lambda: measure_model_exec_corrected(
                        core, "resnet50", batch=8),
                    180.0)
                PROBE_CACHE[("resnet50", 8)] = (dev_ms, fetch_ms)
                exec_extra["model_exec_ms_device"] = round(dev_ms, 2)
                exec_extra["relay_fetch_ms_est"] = round(fetch_ms, 2)
                # batch-8 forward FLOPs / device time vs v5e bf16 peak.
                if platform == "tpu":
                    flops8 = core.repository.get(
                        "resnet50", "").flops_estimate(8)
                    exec_extra["mfu_device"] = round(
                        flops8 / (dev_ms / 1e3) / PEAK_BF16_FLOPS, 5)
                log("resnet50 device exec (batch 8): %.2f ms "
                    "(fetch %.1f ms, mfu %.3f)"
                    % (dev_ms, fetch_ms, exec_extra.get("mfu_device", -1)))
            except Exception as exc:  # noqa: BLE001
                log("corrected exec probe failed (continuing): %s" % exc)
            if relay_blocked():
                raise RuntimeError("relay wedged during probes")
            log("resnet50 warm; measuring over gRPC + tpu shm")
            out_shm = 8 * 1000 * 4 + 1024
            if binary:  # unmeasured pass: fusion/slice kernels compile
                try:
                    run_native(binary, handle.address, "resnet50", batch=8,
                               concurrency=4, shared_memory="tpu",
                               output_shm=out_shm, timeout=60.0, warm=True)
                except Exception as exc:  # noqa: BLE001
                    log("warm pass failed (continuing): %s" % exc)
            with _CompileCounter() as compiles:
                if binary:
                    # Longer windows + more trials than the default:
                    # relay jitter makes 2s windows swing the headline
                    # by +-20% run to run.
                    tput, p50 = run_native(
                        binary, handle.address, "resnet50", batch=8,
                        concurrency=4, shared_memory="tpu",
                        output_shm=out_shm, window_ms=3000, trials=5,
                        timeout=max(30.0, remaining() - 20))
                else:
                    tput, p50 = run_python_harness(
                        "resnet50", 8, 4, "tpu", out_shm,
                        address=handle.address)
            record_stage(
                "resnet50_tpu_shm_grpc", tput, p50,
                {"batch": 8,
                 "vs_baseline": round(tput / BASELINE_RESNET, 4),
                 "overhead_ms": round(max(p50 / 1000.0 - exec_ms, 0.0), 2)
                 if exec_ms is not None else None,
                 "steady_state_compiles": compiles.count,
                 # Served-throughput utilization (relay-latency-bound,
                 # not MXU-bound — mfu_device above is the device view).
                 "mfu_est": round(
                     tput * core.repository.get(
                         "resnet50", "").flops_estimate(1)
                     / PEAK_BF16_FLOPS, 5)
                 if platform == "tpu" else None,
                 **exec_extra})
            # Supplementary: the same path at concurrency 8. The c4
            # headline is round-trip-bound (throughput ~ in-flight
            # batches / RTT), so doubling in-flight shows how much of
            # the ceiling is pipelining vs device.
            if binary and remaining() > 60 and not relay_blocked():
                try:
                    tput8, p508 = run_native(
                        binary, handle.address, "resnet50", batch=8,
                        concurrency=8, shared_memory="tpu",
                        output_shm=out_shm, window_ms=3000, trials=4,
                        timeout=max(30.0, remaining() - 20))
                    record_stage(
                        "resnet50_tpu_shm_grpc_c8", tput8, p508,
                        {"batch": 8, "concurrency": 8,
                         "vs_baseline": round(tput8 / BASELINE_RESNET, 4)})
                except Exception as exc:  # noqa: BLE001
                    log("resnet50 c8 supplement failed (continuing): %s"
                        % exc)
        except Exception as exc:  # noqa: BLE001
            log("resnet50 stage failed: %s" % exc)

    # Stage 5: resnet50 in-process.
    if "resnet50_tpu_shm_grpc" in RESULT["stages"] and remaining() > 90 \
            and not relay_blocked() and stage_wanted("resnet50_inprocess"):
        try:
            # Drain the async exec queue the shm stage left behind: a
            # host round-trip through a fresh computation completes
            # only after everything queued ahead of it (stage 5 scored
            # 0.0 without this — its windows saw no completions).
            import jax
            import numpy as _np

            _ = _np.asarray(jax.device_put(_np.ones(8)) * 2)
            time.sleep(2.0)
            tput, p50 = run_python_harness("resnet50", 8, 4, "none", 0,
                                           core=core, warm_s=1.0)
            record_stage("resnet50_inprocess", tput, p50,
                         {"batch": 8,
                          "vs_baseline": round(tput / BASELINE_INPROCESS, 4),
                          "baseline_src": "ref triton_c_api in-process row",
                          **exec_extra})
        except Exception as exc:  # noqa: BLE001
            log("resnet50_inprocess failed: %s" % exc)

    # Stages 6-8: the remaining BASELINE.md configs (3: BERT dynamic
    # batching over system shm, 4: ensemble bidi streaming with
    # decoupled outputs, 5: LLM generate token streaming). The
    # reference publishes no numbers for these shapes, so the stages
    # carry no vs_baseline — they exist so every BASELINE config has a
    # measured figure on TPU.
    def native_stage(stage_name, model_name, *, batch=1, concurrency=4,
                     shared_memory="none", output_shm=0, streaming=False,
                     window_ms=2000, input_data=None, extra=None,
                     baseline=None, baseline_src="", track_fusion=False,
                     fusion_composing=(), mfu_probe=None):
        if not binary or remaining() < 90:
            return
        if not stage_wanted(stage_name):
            return
        if relay_blocked():
            # A prior device op never returned: the one-client relay
            # is wedged and every later op queues behind it — skipping
            # is honest (running "measurements" against a wedged
            # device is not) and preserves budget for the flush.
            log("%s skipped: relay wedged earlier in this run"
                % stage_name)
            return
        try:
            log("warming %s..." % model_name)
            run_with_watchdog(
                "%s warmup" % model_name,
                lambda: core.repository.load(model_name).warmup(),
                min(240.0, max(120.0, remaining() - 60)))
            data_path = None
            if input_data is not None:
                data_path = "/tmp/bench_%s_input.json" % model_name
                with open(data_path, "w") as f:
                    json.dump(input_data, f)
            common = dict(shared_memory=shared_memory, output_shm=output_shm,
                          streaming=streaming, input_data=data_path,
                          window_ms=window_ms, trials=3, stability=50)
            # One short unmeasured pass so first-call compiles land
            # outside the counted windows.
            try:
                run_native(binary, handle.address, model_name, batch,
                           concurrency, warm=True,
                           timeout=max(30.0, min(120.0, remaining())),
                           **common)
            except Exception as exc:  # noqa: BLE001
                log("%s warm pass failed (continuing): %s"
                    % (stage_name, exc))
            fusion_names = ([model_name] if track_fusion else []) \
                + list(fusion_composing)
            attempts = 0
            with PipelineSampler(core, fusion_names) as sampler:
                while True:
                    attempts += 1
                    # Snapshot inside the loop: a failed attempt's
                    # partial traffic must not pollute the successful
                    # attempt's fusion evidence.
                    counts_before = {name: fusion_stats(core, name)
                                     for name in fusion_names}
                    sampler.reset()
                    try:
                        tput, p50 = run_native(
                            binary, handle.address, model_name, batch,
                            concurrency,
                            timeout=max(30.0, min(240.0, remaining() - 20)),
                            **common)
                        break
                    except Exception as exc:  # noqa: BLE001
                        # A freshly-warmed server right after a heavy
                        # stage occasionally resets the first connection
                        # burst; one settle-and-retry rescues the stage
                        # instead of dropping a BASELINE config from the
                        # record.
                        if attempts >= 2 or remaining() < 60:
                            raise
                        log("%s attempt %d failed (%s) — retrying"
                            % (stage_name, attempts, exc))
                        time.sleep(3.0)
            result = dict(extra or {}, batch=batch, concurrency=concurrency)
            if baseline:
                result["vs_baseline"] = round(tput / baseline, 4)
                result["baseline_src"] = baseline_src
            for name in fusion_names:
                before = counts_before.get(name)
                after = fusion_stats(core, name)
                if before is None or after is None:
                    continue
                d_infer = after["inference_count"] - before["inference_count"]
                d_exec = after["execution_count"] - before["execution_count"]
                if d_infer <= 0:
                    continue
                # < 0.5 proves the dynamic batcher fused
                # (avg fused batch = 1 / ratio). Composing models get
                # a prefixed key so the backbone-step fusion is its
                # own recorded evidence.
                prefix = "" if name == model_name else name + "_"
                result[prefix + "fusion_ratio"] = round(d_exec / d_infer, 4)
                result[prefix + "fused_requests"] = d_infer
                result[prefix + "fused_executions"] = d_exec
                # Executed-batch-size histogram over THIS stage's
                # windows ({size: executions}) plus the pipeline
                # evidence: overlap_ratio is the fraction of
                # device->host fetch wall-clock during which other
                # batches' work (compute dispatch or fetch) was also
                # in flight — fetch time the pipeline kept company
                # instead of serializing behind.
                hist = {
                    size: count - before["batch_hist"].get(size, 0)
                    for size, count in sorted(after["batch_hist"].items())
                }
                hist = {s: c for s, c in hist.items() if c > 0}
                if hist:
                    result[prefix + "fused_batch_hist"] = hist
                d_fetch = after["fetch_ns"] - before["fetch_ns"]
                d_overlap = after["overlap_ns"] - before["overlap_ns"]
                if d_fetch > 0:
                    result[prefix + "overlap_ratio"] = round(
                        d_overlap / d_fetch, 4)
                # Gauges sampled DURING the measured windows (the
                # after-run values would always read the drained 0).
                result[prefix + "batch_pending_depth_max"] = \
                    sampler.max_pending.get(name, after["pending_count"])
                result[prefix + "batch_inflight_max"] = \
                    sampler.max_inflight.get(name, after["inflight_count"])
                result[prefix + "adaptive_queue_delay_us"] = \
                    after["queue_delay_us"]
            # Device-side residual for the VERDICT contract: every TPU
            # stage records model_exec_ms_device + mfu_device. The
            # probe runs AFTER the measured windows (same warm model,
            # no contention with counted traffic).
            if mfu_probe and platform == "tpu" and not relay_blocked() \
                    and remaining() > 90:
                probe_model, probe_batch, probe_seq = mfu_probe
                try:
                    if (probe_model, probe_batch) in PROBE_CACHE:
                        dev_ms, fetch_ms = PROBE_CACHE[
                            (probe_model, probe_batch)]
                    else:
                        dev_ms, fetch_ms = run_with_watchdog(
                            "%s mfu probe" % stage_name,
                            lambda: measure_model_exec_corrected(
                                core, probe_model, batch=probe_batch),
                            150.0)
                        PROBE_CACHE[(probe_model, probe_batch)] = (
                            dev_ms, fetch_ms)
                    prefix = ("" if probe_model == model_name
                              else probe_model + "_")
                    result[prefix + "model_exec_ms_device"] = round(dev_ms, 2)
                    result[prefix + "relay_fetch_ms_est"] = round(fetch_ms, 2)
                    result[prefix + "mfu_probe_batch"] = probe_batch
                    flops = core.repository.get(
                        probe_model, "").flops_estimate(probe_batch,
                                                        probe_seq)
                    if flops:
                        result[prefix + "mfu_device"] = round(
                            flops / (dev_ms / 1e3) / PEAK_BF16_FLOPS, 5)
                    log("%s device exec (batch %d): %.2f ms (mfu %.4f)"
                        % (probe_model, probe_batch, dev_ms,
                           result.get(prefix + "mfu_device", -1)))
                except Exception as exc:  # noqa: BLE001
                    log("%s mfu probe failed (continuing): %s"
                        % (stage_name, exc))
            record_stage(stage_name, tput, p50, result)
        except Exception as exc:  # noqa: BLE001
            log("%s failed: %s" % (stage_name, exc))

    # Config 3: BERT-base, dynamic batching fuses concurrent variable
    # length requests server-side; I/O over system shared memory.
    # Concurrency 64: the served round trip has a hard ~65 ms relay
    # fetch floor, so throughput = in-flight requests / latency — and
    # the batcher turns those 64 into a few MXU calls (fusion_ratio is
    # the recorded proof).
    native_stage("bert_grpc_sysshm", "bert_base", concurrency=64,
                 shared_memory="system", output_shm=4096,
                 baseline=BASELINE_R3["bert_grpc_sysshm"],
                 baseline_src="r03 regenerated (BASELINE.md)",
                 track_fusion=True,
                 # exec probe pads seq to the 128 bucket (the corrected
                 # probe's dynamic-dim default) at a preferred batch.
                 mfu_probe=("bert_base", 32, 128))
    # Config 3b: dyna_sequence — stateful sequence serving through the
    # sequence scheduler (BASELINE config 3's dyna_sequence path). 12
    # concurrent sequences under the Oldest strategy: each step
    # carries device-resident implicit state and dispatches through
    # the dynamic batcher, so steps from distinct sequences fuse
    # (fusion_ratio < 1 and mean_fused_step_batch > 1 are the proof).
    if remaining() > 90 and stage_wanted("dyna_sequence_inprocess"):
        try:
            run_with_watchdog(
                "dyna_sequence load",
                lambda: core.repository.load("dyna_sequence"),
                min(120.0, max(30.0, remaining() - 60)))
            before = fusion_stats(core, "dyna_sequence")
            tput, p50 = run_python_harness(
                "dyna_sequence", 1, 12, "none", 0, core=core,
                warm_s=1.0, sequence_length=10)
            after = fusion_stats(core, "dyna_sequence")
            extra = {"concurrency": 12, "sequence_length": 10}
            if before and after:
                d_infer = after["inference_count"] - before["inference_count"]
                d_exec = after["execution_count"] - before["execution_count"]
                if d_infer > 0 and d_exec > 0:
                    extra["fusion_ratio"] = round(d_exec / d_infer, 4)
                    extra["mean_fused_step_batch"] = round(
                        d_infer / d_exec, 2)
                    extra["fused_requests"] = d_infer
                    extra["fused_executions"] = d_exec
            seq = sequence_stats(core, "dyna_sequence")
            if seq:
                extra["sequences_started"] = seq["sequences_started"]
                extra["sequence_steps"] = seq["step_count"]
                extra["sequence_slot_total"] = seq["slot_total"]
                extra["sequence_idle_reclaimed"] = \
                    seq["idle_reclaimed_total"]
            record_stage("dyna_sequence_inprocess", tput, p50, extra)
        except Exception as exc:  # noqa: BLE001
            log("dyna_sequence_inprocess failed: %s" % exc)

    # Config 3d: response cache — hot-set replay against simple_cache
    # (the `simple` add/sub model with response_cache.enable + a
    # dynamic batcher). Cold phase: content-unique requests, all
    # misses through the batcher. Warm phase: a 64-request hot set
    # replayed, all hits bypassing queue/batcher/execution. The
    # single-flight burst proves N identical concurrent requests
    # execute the model exactly once. Acceptance: warm-hit tput >= 5x
    # cold-miss tput and singleflight_executions == 1.
    if remaining() > 60 and stage_wanted("response_cache"):
        try:
            run_with_watchdog(
                "simple_cache load",
                lambda: core.repository.load("simple_cache"),
                min(120.0, max(30.0, remaining() - 60)))
            extra = run_cache_measure(core)
            record_stage("response_cache", extra.get("warm_hit_tput", 0.0),
                         extra.get("warm_hit_p50_us", 0.0), extra)
        except Exception as exc:  # noqa: BLE001
            log("response_cache failed: %s" % exc)

    # Config 3e: multi-tenant QoS under overload — priority-2 bulk
    # saturates a bounded queue (8 deep, shed watermark 0.9) while a
    # priority-1 foreground keeps sending. Acceptance: priority-1 p99
    # <= 2x its unloaded baseline with 100% goodput (bulk absorbs
    # every reject/shed), and mixed-priority c16 fusion within 10% of
    # single-class (QoS costs dispatch order, not batch efficiency).
    if remaining() > 60 and stage_wanted("qos_overload"):
        try:
            extra = run_qos_measure(core)
            record_stage("qos_overload", extra.get("p1_tput", 0.0),
                         extra.get("p1_loaded_p50_us", 0.0), extra)
            if extra.get("p1_goodput_pct", 0.0) < 100.0:
                log("qos_overload: priority-1 goodput %.2f%% below "
                    "100%%" % extra.get("p1_goodput_pct", 0.0))
            if extra.get("p1_p99_vs_unloaded", 0.0) > 2.0:
                log("qos_overload: priority-1 p99 %.2fx unloaded "
                    "exceeds the 2x gate"
                    % extra.get("p1_p99_vs_unloaded", 0.0))
        except Exception as exc:  # noqa: BLE001
            log("qos_overload failed: %s" % exc)

    # Config 3f: replica serving — data-parallel scaling (1 vs 4
    # per-device replicas of a delay-bound model under one closed
    # loop) plus the degrade-one blast-radius timeline (replica 2 of 4
    # hard-degraded mid-run: goodput holds 100% via bounded
    # re-dispatch, throughput degrades toward 3/4 after ejection, and
    # recovers within 20% of the pre-fault rate once the supervisor
    # readmits). Acceptance: scaling_4v1 >= 2.5x, degrade goodput
    # 100%, recovery_vs_prefault >= 0.8.
    if remaining() > 90 and stage_wanted("replica_scaling"):
        try:
            extra = run_replica_measure(core)
            record_stage("replica_scaling", extra.get("tput_4", 0.0),
                         extra.get("p50_4_us", 0.0), extra)
            if extra.get("scaling_4v1", 0.0) < 2.5:
                log("replica_scaling: %.2fx at 4 replicas is under "
                    "the 2.5x gate" % extra.get("scaling_4v1", 0.0))
            if extra.get("degrade_goodput_pct", 0.0) < 100.0:
                log("replica_scaling: degrade-one goodput %.2f%% "
                    "below 100%%"
                    % extra.get("degrade_goodput_pct", 0.0))
            if extra.get("recovery_vs_prefault", 0.0) < 0.8:
                log("replica_scaling: post-readmission throughput "
                    "%.3fx pre-fault is under the 0.8x gate"
                    % extra.get("recovery_vs_prefault", 0.0))
        except Exception as exc:  # noqa: BLE001
            log("replica_scaling failed: %s" % exc)

    # Mesh-slice serving (docs/sharded_serving.md): 1 vs 2 tp-sharded
    # slices of a delay-bound model under one closed loop, plus the
    # kill-one-chip timeline (chaos device=0 fails every execution
    # touching the chip: goodput holds 100% via re-dispatch to the
    # sibling slice, the WHOLE slice ejects, and the supervisor
    # readmits it after the chip heals). Acceptance: scaling_2v1 >=
    # 1.8x, degrade goodput 100%, >=1 ejection and readmission.
    if remaining() > 60 and stage_wanted("mesh_sharded"):
        try:
            extra = run_mesh_measure(core)
            record_stage("mesh_sharded", extra.get("tput_2slice", 0.0),
                         extra.get("p50_2slice_us", 0.0), extra)
            if extra.get("scaling_2v1", 0.0) < 1.8:
                log("mesh_sharded: %.2fx at 2 slices is under the "
                    "1.8x gate" % extra.get("scaling_2v1", 0.0))
            if extra.get("degrade_goodput_pct", 0.0) < 100.0:
                log("mesh_sharded: kill-one-chip goodput %.2f%% "
                    "below 100%%"
                    % extra.get("degrade_goodput_pct", 0.0))
            if extra.get("readmissions", 0) < 1:
                log("mesh_sharded: the killed slice was never "
                    "readmitted")
        except Exception as exc:  # noqa: BLE001
            log("mesh_sharded failed: %s" % exc)

    # Config 3d: span-tracing overhead — the identical closed loop on
    # add_sub_large (4 MiB tensors, the ms-scale request shape tracing
    # exists for) with tracing OFF vs trace_rate=1 (every request
    # records a full span tree + compact record). Gate: <5% throughput
    # cost; with this held, the perf harness can run --trace in
    # production without distorting what it measures.
    if remaining() > 45 and stage_wanted("tracing_overhead"):
        try:
            run_with_watchdog(
                "add_sub_large load",
                lambda: core.repository.load("add_sub_large"),
                min(120.0, max(30.0, remaining() - 60)))
            extra = run_tracing_measure(core)
            record_stage("tracing_overhead",
                         extra.get("trace_on_tput", 0.0),
                         extra.get("trace_on_p50_us", 0.0), extra)
            if not extra.get("overhead_ok", True):
                log("tracing overhead %.2f%% exceeds the 5%% gate"
                    % extra.get("overhead_pct", 0.0))
        except Exception as exc:  # noqa: BLE001
            log("tracing_overhead failed: %s" % exc)

    # Config 3g: latency-histogram (telemetry) overhead — the same
    # closed loop on add_sub_large with the always-on histogram
    # registry disabled vs enabled. Gate: <2% throughput cost at
    # trace_rate=0, so the SLO histograms can stay on in production
    # unconditionally (the whole point of "always-on").
    if remaining() > 45 and stage_wanted("telemetry_overhead"):
        try:
            run_with_watchdog(
                "add_sub_large load",
                lambda: core.repository.load("add_sub_large"),
                min(120.0, max(30.0, remaining() - 60)))
            extra = run_telemetry_measure(core)
            record_stage("telemetry_overhead",
                         extra.get("telemetry_on_tput", 0.0),
                         extra.get("telemetry_on_p50_us", 0.0), extra)
            if not extra.get("overhead_ok", True):
                log("telemetry overhead %.2f%% exceeds the 2%% gate"
                    % extra.get("overhead_pct", 0.0))
        except Exception as exc:  # noqa: BLE001
            log("telemetry_overhead failed: %s" % exc)

    # Config 3i: flight-recorder capture overhead — the same closed
    # loop on add_sub_large with the always-on scratch span capture
    # disabled vs enabled (nothing is kept on clean traffic, so this
    # is the pure cost of having forensics armed). Gate: <2%
    # throughput, so the tail-retention layer can stay on in
    # production unconditionally.
    if remaining() > 45 and stage_wanted("flight_overhead"):
        try:
            run_with_watchdog(
                "add_sub_large load",
                lambda: core.repository.load("add_sub_large"),
                min(120.0, max(30.0, remaining() - 60)))
            extra = run_flight_measure(core)
            record_stage("flight_overhead",
                         extra.get("flight_on_tput", 0.0),
                         extra.get("flight_on_p50_us", 0.0), extra)
            if not extra.get("overhead_ok", True):
                log("flight capture overhead %.2f%% exceeds the 2%% "
                    "gate" % extra.get("overhead_pct", 0.0))
        except Exception as exc:  # noqa: BLE001
            log("flight_overhead failed: %s" % exc)

    # Config 3h: relay-fetch A/B — the overlapped output-fetch
    # subsystem (client_tpu.server.fetch) vs the legacy serial
    # np.asarray on the identical multi-output 4 MiB fetch_bench
    # pair: client throughput/p50 per arm plus the server-side
    # relay_fetch p50 window deltas and their ratio. On the
    # accelerator this stage is ROADMAP item 1's success metric (the
    # ~67 ms relay tax measured with and without the subsystem).
    if remaining() > 45 and stage_wanted("relay_fetch_ab"):
        try:
            run_with_watchdog(
                "fetch_bench load",
                lambda: (core.repository.load("fetch_bench"),
                         core.repository.load("fetch_bench_legacy")),
                min(120.0, max(30.0, remaining() - 60)))
            extra = run_fetch_measure(core)
            record_stage("relay_fetch_ab",
                         extra.get("overlapped_tput", 0.0),
                         extra.get("overlapped_p50_us", 0.0), extra)
            log("relay_fetch p50: overlapped %.0f us vs legacy %.0f "
                "us (%.2fx) over %d executions"
                % (extra.get("relay_fetch_p50_overlapped_us", 0.0),
                   extra.get("relay_fetch_p50_legacy_us", 0.0),
                   extra.get("relay_fetch_p50_speedup", 0.0),
                   extra.get("relay_fetch_executions", 0)))
        except Exception as exc:  # noqa: BLE001
            log("relay_fetch_ab failed: %s" % exc)

    # Config 3c: failover + hedging across a 2-server fleet (the
    # EndpointPool client). Three measurements: one endpoint latency-
    # spiked WITHOUT hedging (the tail to beat), the same spike WITH
    # hedging (p99 must drop while the hedge ratio stays inside the
    # budget), and one endpoint hard-killed mid-run (goodput must hold
    # 100% — every failure failed over).
    if remaining() > 150 and stage_wanted("failover_hedging"):
        try:
            from client_tpu import robust as _robust

            _robust.reset_retry_total()
            spiked, _ = run_fleet_measure(hedge_max_ratio=0.0,
                                          spike_ms=200.0)
            hedged, hedged_pool = run_fleet_measure(hedge_max_ratio=0.05,
                                                    spike_ms=200.0)
            killed, killed_pool = run_fleet_measure(kill_after_s=2.0,
                                                    window_ms=3000,
                                                    trials=2)
            attempted = killed.completed_count + killed.error_count
            extra = {
                "p99_spiked_unhedged_us": round(
                    spiked.latency_percentiles.get(99, 0.0)),
                "p99_spiked_hedged_us": round(
                    hedged.latency_percentiles.get(99, 0.0)),
                "hedges_fired": hedged_pool["hedges_fired"],
                "hedges_won": hedged_pool["hedges_won"],
                "hedge_ratio": round(
                    hedged_pool["hedges_fired"]
                    / max(hedged_pool["requests"], 1), 4),
                "hedge_delay_ms": hedged_pool["hedge_delay_ms"],
                "kill_errors": killed.error_count,
                "kill_goodput_pct": round(
                    killed.completed_count / attempted * 100.0, 2)
                if attempted else 0.0,
                "kill_failovers": killed_pool["failovers"],
                "kill_ejections": killed_pool["ejections"],
            }
            if extra["p99_spiked_hedged_us"]:
                extra["p99_hedging_speedup"] = round(
                    extra["p99_spiked_unhedged_us"]
                    / extra["p99_spiked_hedged_us"], 2)
            record_stage("failover_hedging", hedged.throughput,
                         hedged.latency_percentiles.get(50, 0.0), extra)
        except Exception as exc:  # noqa: BLE001
            log("failover_hedging failed: %s" % exc)

    # Config 4: ensemble (preprocess -> resnet50 -> postprocess) over
    # bidi streaming gRPC with decoupled outputs. Concurrency 32 for
    # the same latency-floor reason; the backbone step fuses across
    # concurrent stream requests through resnet50's own dynamic
    # batcher (fusion_ratio on the composing model is the proof).
    native_stage("ensemble_stream_grpc", "ensemble_image", concurrency=32,
                 streaming=True,
                 baseline=BASELINE_R3["ensemble_stream_grpc"],
                 baseline_src="r03 regenerated (BASELINE.md)",
                 track_fusion=True, fusion_composing=("resnet50",),
                 # the ensemble's device time lives in its resnet50
                 # backbone step — probe that at its preferred batch.
                 mfu_probe=("resnet50", 8, 0))
    # Config 5: LLM generate endpoint, decoupled token streaming
    # (device-side chunked decode: one host fetch per 8 tokens).
    # Inputs are pinned — random data would draw a huge max_tokens and
    # clamp to max_seq, benchmarking 1022-token generations.
    llm_max_tokens = 32
    native_stage("llm_generate_stream", "llm_tiny", concurrency=4,
                 streaming=True, window_ms=4000,
                 input_data={"data": [{
                     "text_input": ["Benchmark prompt: the quick brown "
                                    "fox jumps over the lazy dog."],
                     "max_tokens": [llm_max_tokens],
                     "ignore_eos": [True]}]},
                 extra={"tokens_per_request": llm_max_tokens})
    llm_stage = RESULT["stages"].get("llm_generate_stream")
    if llm_stage:
        llm_stage["tokens_per_sec"] = round(
            llm_stage["throughput"] * llm_stage["tokens_per_request"], 1)
        llm_stage["vs_baseline"] = round(
            llm_stage["tokens_per_sec"] / BASELINE_R3["llm_tokens_per_sec"],
            4)
        llm_stage["baseline_src"] = "r03 regenerated (BASELINE.md), tokens/s"
        if platform == "tpu":
            try:
                fpt = core.repository.get("llm_tiny", "").flops_per_token()
                llm_stage["flops_per_token"] = round(fpt)
                llm_stage["mfu_serving"] = round(
                    llm_stage["tokens_per_sec"] * fpt / PEAK_BF16_FLOPS, 7)
            except Exception as exc:  # noqa: BLE001
                log("llm mfu attach failed: %s" % exc)
        flush_result()

    # Config 5 LLM metrics proper: the genai harness measures TTFT and
    # inter-token latency over the decoupled stream (the numbers LLM
    # serving is actually judged by). Attached to the llm stage.
    if llm_stage and remaining() > 90:
        try:
            export = "/tmp/bench_genai.json"
            proc = subprocess.run(
                [sys.executable, "-m", "client_tpu.genai.main",
                 "-m", "llm_tiny", "-u", handle.address,
                 "--concurrency", "2", "--num-prompts", "6",
                 "--output-tokens-mean", str(llm_max_tokens),
                 "--measurement-interval", "3000", "--max-trials", "3",
                 "--export-json", export],
                capture_output=True, text=True, cwd=str(REPO),
                timeout=max(60.0, min(240.0, remaining() - 20)))
            if proc.returncode != 0:
                raise RuntimeError("genai rc=%d: %s"
                                   % (proc.returncode, proc.stderr[-400:]))
            with open(export) as f:
                doc = json.load(f)
            stats = doc["experiments"][0]
            for key, out_name in (
                ("time_to_first_token_ms", "ttft_ms"),
                ("inter_token_latency_ms", "itl_ms"),
            ):
                if key in stats:
                    llm_stage[out_name] = {
                        k: round(v, 2)
                        for k, v in stats[key].items()
                        if k in ("mean", "p50", "p99")}
            itl = llm_stage.get("itl_ms")
            if itl and itl.get("p99"):
                # > 1 = better than the r03 anchor (lower tail latency).
                llm_stage["itl_p99_improvement"] = round(
                    BASELINE_R3["llm_itl_p99_ms"] / itl["p99"], 2)
            flush_result()
            log("genai TTFT/ITL attached: %s / %s"
                % (llm_stage.get("ttft_ms"), llm_stage.get("itl_ms")))
        except Exception as exc:  # noqa: BLE001
            log("genai stage failed: %s" % exc)

    # Config 5b: paged-KV continuous batching A/B (ROADMAP item 2).
    # Dense c4 baseline vs the paged arm at c4/c16 (c64 when budget
    # allows): tokens/s, TTFT/ITL, pages-used peak, prefix hit ratio,
    # token parity, and the cancel+crash leak check.
    if remaining() > 150 and stage_wanted("llm_continuous"):
        try:
            concs = (4, 16, 64) if remaining() > 300 else (4, 16)
            extra = run_with_watchdog(
                "llm_continuous measure",
                lambda: run_llm_continuous_measure(concurrencies=concs),
                min(420.0, max(120.0, remaining() - 30)))
            top = extra.get("paged_c%d" % max(concs), {})
            record_stage("llm_continuous",
                         top.get("tokens_per_sec", 0.0),
                         top.get("itl_p50_ms", 0.0) * 1000.0, extra)
            log("llm_continuous: dense c4 %.0f tok/s; paged %s; "
                "parity=%s leak=%d"
                % (extra.get("dense_c4", {}).get("tokens_per_sec", 0),
                   ", ".join(
                       "c%d %.0f tok/s (%.1fx, itl p99 %.2fx)"
                       % (c,
                          extra["paged_c%d" % c]["tokens_per_sec"],
                          extra["paged_c%d" % c].get(
                              "speedup_vs_dense_c4", 0.0),
                          extra["paged_c%d" % c].get(
                              "itl_p99_vs_dense_c4", 0.0))
                       for c in concs if ("paged_c%d" % c) in extra),
                   extra.get("token_parity"),
                   extra.get("pages_used_final", -1)))
        except Exception as exc:  # noqa: BLE001
            log("llm_continuous failed: %s" % exc)

    # Config 4b: device-resident ensemble dataflow A/B (ROADMAP
    # item 1's ensemble form). Distinct-input phase at c16 for the
    # backbone fusion ratio, pinned hot set for the stage-cache
    # short-circuit throughput gap, golden parity, and the span gate
    # (ensemble_step present, zero relay_fetch).
    if remaining() > 45 and stage_wanted("ensemble_dataflow_ab"):
        try:
            extra = run_with_watchdog(
                "ensemble_dataflow measure",
                run_ensemble_dataflow_measure,
                min(180.0, max(60.0, remaining() - 30)))
            record_stage("ensemble_dataflow_ab",
                         extra.get("dataflow_tput", 0.0),
                         extra.get("dataflow_p50_us", 0.0), extra)
            log("ensemble_dataflow: hot %.0f/s vs legacy %.0f/s "
                "(%.2fx); fusion %.3f over %d backbone rows; "
                "parity=%s; spans step=%d relay_fetch=%d"
                % (extra.get("dataflow_tput", 0.0),
                   extra.get("legacy_tput", 0.0),
                   extra.get("speedup", 0.0),
                   extra.get("fusion_ratio", 1.0),
                   extra.get("backbone_inferences", 0),
                   extra.get("golden_parity"),
                   extra.get("ensemble_step_spans", 0),
                   extra.get("interior_relay_fetch_spans", -1)))
        except Exception as exc:  # noqa: BLE001
            log("ensemble_dataflow_ab failed: %s" % exc)

    # Reconcile the probe label with the final relay state: a stall
    # that later recovered (stages ran) must not read as "model stages
    # absent because wedged", and a relay that wedged AFTER a clean
    # probe must not read as "ok".
    stalled_event = RELAY_STALL["event"]
    if stalled_event is not None and not stalled_event.is_set():
        RESULT["device_probe"] = "stalled: relay wedged mid-run"
    elif str(RESULT.get("device_probe", "")).startswith("stalled"):
        RESULT["device_probe"] = "stalled-then-recovered"
    flush_result()
    handle.stop()
    log("done")


if __name__ == "__main__":
    main()
