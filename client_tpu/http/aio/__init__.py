"""asyncio HTTP/REST client over aiohttp — mirror of client_tpu.http
(parity: reference tritonclient.http.aio, http/aio/__init__.py:92+)."""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional, Sequence

import aiohttp

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu._plugin import InferenceServerClientBase
from client_tpu.http import _endpoints as ep
from client_tpu.http._client import InferResult
from client_tpu.protocol.http_wire import HEADER_LEN, encode_infer_request
from client_tpu.utils import InferenceServerException

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
]


class InferenceServerClient(InferenceServerClientBase):
    """asyncio HTTP client. ``url`` may be a comma-separated endpoint
    list (or a list), or a shared
    :class:`client_tpu.robust.EndpointPool` may be passed as
    ``endpoint_pool``: ``infer`` then routes least-outstanding across
    healthy endpoints, fails over on retryable errors, and hedges
    tail-slow requests within the pool's budget; the pool's
    thread-based prober (stdlib HTTP, off the event loop) readmits
    ejected endpoints. With a pool, ``circuit_breaker`` is ignored.

    ``tracer`` (:class:`client_tpu.tracing.ClientTracer`) records a
    client-side span per ``infer`` and propagates its W3C
    ``traceparent`` header (caller-supplied traceparent wins)."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        conn_limit: int = 100,
        conn_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy=None,
        circuit_breaker=None,
        endpoint_pool=None,
        tracer=None,
    ):
        super().__init__()
        from client_tpu.robust import EndpointPool

        urls = (endpoint_pool.urls if endpoint_pool is not None
                else EndpointPool.split_url(url))
        if not urls:
            raise InferenceServerException("invalid url '%s'" % url)
        self._owns_pool = endpoint_pool is None and len(urls) > 1
        self._endpoint_pool = (endpoint_pool if endpoint_pool is not None
                               else (EndpointPool(urls) if len(urls) > 1
                                     else None))
        # client_tpu.robust wiring (same contract as the sync client).
        self._tracer = tracer
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker if self._endpoint_pool is None \
            else None
        self._bases = {
            u: (u if "://" in u else (("https://" if ssl else "http://") + u)
                ).rstrip("/")
            for u in urls
        }
        self._base = self._bases[urls[0]]
        self._verbose = verbose
        connector = aiohttp.TCPConnector(limit=conn_limit, ssl=ssl_context
                                         if ssl else False)
        self._session = aiohttp.ClientSession(
            connector=connector,
            timeout=aiohttp.ClientTimeout(total=conn_timeout),
        )
        if self._endpoint_pool is not None:
            from client_tpu.http._endpoints import probe_http_ready

            timeout = self._endpoint_pool.probe_timeout_s
            self._endpoint_pool.ensure_prober(
                lambda u, _ssl=ssl: probe_http_ready(u, timeout, _ssl))

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc_value, traceback):
        await self.close()

    async def close(self):
        if self._endpoint_pool is not None and self._owns_pool:
            self._endpoint_pool.close()
        await self._session.close()

    def pool_stats(self) -> Optional[dict]:
        """EndpointPool snapshot (hedges/failovers/ejections + per-
        endpoint health); None for a single-endpoint client."""
        return (self._endpoint_pool.stats()
                if self._endpoint_pool is not None else None)

    async def _request(self, method: str, path: str, body=None, headers=None,
                       timeout: Optional[float] = None,
                       base: Optional[str] = None):
        headers = self._call_plugin(dict(headers) if headers else {})
        kwargs = {}
        if timeout is not None:
            kwargs["timeout"] = aiohttp.ClientTimeout(total=timeout)
        try:
            async with self._session.request(
                method, (base or self._base) + path, data=body,
                headers=headers or {}, **kwargs
            ) as response:
                payload = await response.read()
                return response.status, dict(response.headers), payload
        except asyncio.TimeoutError as e:
            raise InferenceServerException(
                "request timed out after %.3fs" % (timeout or 0),
                status="DEADLINE_EXCEEDED") from e
        except aiohttp.ClientError as e:
            raise InferenceServerException(
                "connection failed: %s" % e, status="UNAVAILABLE") from e

    @staticmethod
    def _raise_if_error(status, resp_headers, payload):
        lowered = {k.lower(): v for k, v in resp_headers.items()}
        ep.raise_if_error(
            status, payload,
            retry_after_s=ep.parse_retry_after(lowered.get("retry-after")))

    async def _get_json(self, path, headers=None, method="GET", body=None):
        status, resp_headers, payload = await self._request(
            method, path, body, headers)
        self._raise_if_error(status, resp_headers, payload)
        return json.loads(payload) if payload else {}

    async def _get_json_fleet(self, path, headers=None, method="GET",
                              body=None):
        """Control-plane verb against EVERY endpoint (shm registration,
        model load/unload) — fleet members are replicas, so
        per-replica state must be applied to all of them."""
        result = None
        for base in self._bases.values():
            status, resp_headers, payload = await self._request(
                method, path, body, headers, base=base)
            self._raise_if_error(status, resp_headers, payload)
            result = json.loads(payload) if payload else {}
        return result

    # -- health / metadata ----------------------------------------------

    async def is_server_live(self, headers=None, client_timeout=None) -> bool:
        """``client_timeout`` bounds the probe (sync/gRPC parity)."""
        status, _, _ = await self._request("GET", "/v2/health/live",
                                           headers=headers,
                                           timeout=client_timeout)
        return status == 200

    async def is_server_ready(self, headers=None,
                              client_timeout=None) -> bool:
        status, _, _ = await self._request("GET", "/v2/health/ready",
                                           headers=headers,
                                           timeout=client_timeout)
        return status == 200

    async def is_model_ready(self, model_name, model_version="",
                             headers=None, client_timeout=None) -> bool:
        status, _, _ = await self._request(
            "GET", ep.ready_path(model_name, model_version), headers=headers,
            timeout=client_timeout,
        )
        return status == 200

    async def get_server_metadata(self, headers=None) -> dict:
        return await self._get_json("/v2", headers)

    async def get_model_metadata(self, model_name, model_version="",
                                 headers=None) -> dict:
        return await self._get_json(
            ep.model_path(model_name, model_version), headers
        )

    async def get_model_config(self, model_name, model_version="",
                               headers=None) -> dict:
        return await self._get_json(
            ep.config_path(model_name, model_version), headers
        )

    async def get_model_repository_index(self, headers=None) -> list:
        return await self._get_json(ep.repo_index_path(), headers,
                                    method="POST", body=b"{}")

    async def load_model(self, model_name, headers=None, config=None):
        await self._get_json_fleet(ep.repo_load_path(model_name), headers,
                                   method="POST",
                                   body=ep.load_model_body(config))

    async def unload_model(self, model_name, headers=None):
        await self._get_json_fleet(ep.repo_unload_path(model_name), headers,
                                   method="POST",
                                   body=ep.unload_model_body())

    async def get_inference_statistics(self, model_name="", model_version="",
                                       headers=None) -> dict:
        return await self._get_json(
            ep.stats_path(model_name, model_version), headers
        )

    # -- trace / log settings --------------------------------------------

    async def update_trace_settings(self, model_name="", settings=None,
                                    headers=None) -> dict:
        """Asyncio mirror of the sync client's trace-settings verbs."""
        return await self._get_json(
            ep.trace_path(model_name), headers, method="POST",
            body=json.dumps(settings or {}).encode())

    async def get_trace_settings(self, model_name="", headers=None) -> dict:
        return await self._get_json(ep.trace_path(model_name), headers)

    async def update_log_settings(self, settings, headers=None) -> dict:
        return await self._get_json(
            ep.logging_path(), headers, method="POST",
            body=json.dumps(settings or {}).encode())

    async def get_log_settings(self, headers=None) -> dict:
        return await self._get_json(ep.logging_path(), headers)

    # -- shared memory ---------------------------------------------------

    async def get_system_shared_memory_status(self, region_name="",
                                              headers=None) -> list:
        return await self._get_json(
            ep.shm_status_path("system", region_name), headers
        )

    async def register_system_shared_memory(self, name, key, byte_size,
                                            offset=0, headers=None):
        await self._get_json_fleet(
            ep.shm_register_path("system", name), headers, method="POST",
            body=ep.system_shm_register_body(key, byte_size, offset),
        )

    async def unregister_system_shared_memory(self, name="", headers=None):
        await self._get_json_fleet(ep.shm_unregister_path("system", name),
                                   headers, method="POST", body=b"{}")

    async def get_tpu_shared_memory_status(self, region_name="",
                                           headers=None) -> list:
        return await self._get_json(
            ep.shm_status_path("tpu", region_name), headers
        )

    async def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                         byte_size, headers=None):
        await self._get_json_fleet(
            ep.shm_register_path("tpu", name), headers, method="POST",
            body=ep.tpu_shm_register_body(raw_handle, device_id, byte_size),
        )

    async def unregister_tpu_shared_memory(self, name="", headers=None):
        await self._get_json_fleet(ep.shm_unregister_path("tpu", name),
                                   headers, method="POST", body=b"{}")

    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- inference -------------------------------------------------------

    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        parameters: Optional[dict] = None,
    ) -> InferResult:
        body, json_len = encode_infer_request(
            inputs=inputs, outputs=outputs, request_id=request_id,
            sequence_id=sequence_id, sequence_start=sequence_start,
            sequence_end=sequence_end, priority=priority, timeout=timeout,
            parameters=parameters,
        )
        request_headers = dict(headers) if headers else {}
        client_span = None
        if self._tracer is not None:
            client_span = self._tracer.start_span(
                "client_infer", model_name, request_id, request_headers)
            client_span.attrs["transport"] = "http-aio"
            request_headers = client_span.inject(request_headers)
        if json_len is not None:
            request_headers[HEADER_LEN] = str(json_len)
            request_headers["Content-Type"] = "application/octet-stream"
        else:
            request_headers["Content-Type"] = "application/json"

        path = ep.infer_path(model_name, model_version)

        def _decode(status, resp_headers, payload):
            self._raise_if_error(status, resp_headers, payload)
            lowered = {k.lower(): v for k, v in resp_headers.items()}
            header_len = lowered.get(HEADER_LEN.lower())
            return InferResult.from_response_body(
                payload, int(header_len) if header_len else None
            )

        async def _issue():
            if self._endpoint_pool is not None:
                from client_tpu.robust import call_with_retry_pool_async

                async def _pool_attempt(state, remaining):
                    return _decode(*await self._request(
                        "POST", path, body=body, headers=request_headers,
                        timeout=remaining, base=self._bases[state.url],
                    ))

                return await call_with_retry_pool_async(
                    _pool_attempt, self._endpoint_pool, self._retry_policy,
                    deadline_s=client_timeout, sequence_id=sequence_id,
                    sequence_end=sequence_end,
                )

            async def _attempt(remaining):
                return _decode(*await self._request(
                    "POST", path, body=body,
                    headers=request_headers, timeout=remaining,
                ))

            from client_tpu.robust import call_with_retry_async

            return await call_with_retry_async(
                _attempt, self._retry_policy, self._breaker,
                deadline_s=client_timeout,
            )

        if client_span is None:
            return await _issue()
        try:
            result = await _issue()
        except BaseException as e:
            client_span.finish(e)
            raise
        client_span.finish()
        return result
