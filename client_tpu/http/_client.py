"""Synchronous + callback-async HTTP/REST client for the KServe-v2
protocol (binary tensor extension included).

API-parity surface with the reference tritonclient.http
InferenceServerClient (http/_client.py:102+). The reference pools
geventhttpclient connections; here a thread-safe pool of stdlib
``http.client`` keep-alive connections plus a worker pool provides
the same concurrency model without extra dependencies.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional, Sequence, Tuple
from urllib.parse import quote, urlparse

import http.client

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu._plugin import InferenceServerClientBase
from client_tpu.http import _endpoints as ep
from client_tpu.protocol.http_wire import (
    HEADER_LEN,
    DecodedOutput,
    compress_body,
    decode_infer_response,
    decompress_body,
    encode_infer_request,
)
from client_tpu.utils import InferenceServerException


class InferResult:
    """Result wrapper over a decoded HTTP inference response."""

    def __init__(self, header: dict, outputs: Dict[str, DecodedOutput]):
        self._header = header
        self._outputs = outputs

    @classmethod
    def from_response_body(
        cls, body: bytes, header_length: Optional[int] = None
    ) -> "InferResult":
        header, outputs = decode_infer_response(body, header_length)
        return cls(header, outputs)

    def get_response(self) -> dict:
        return self._header

    def get_output(self, name: str) -> Optional[dict]:
        for entry in self._header.get("outputs", []):
            if entry.get("name") == name:
                return entry
        return None

    def as_numpy(self, name: str):
        decoded = self._outputs.get(name)
        return decoded.as_numpy() if decoded is not None else None

    def get_parameters(self) -> dict:
        return self._header.get("parameters", {})


class InferAsyncRequest:
    """Handle returned by async_infer; get_result() joins the worker."""

    def __init__(self, future, verbose: bool = False):
        self._future = future
        self._verbose = verbose

    def get_result(self, block: bool = True, timeout: Optional[float] = None
                   ) -> InferResult:
        if not block and not self._future.done():
            raise InferenceServerException("result is not ready")
        result = self._future.result(timeout=timeout)
        if isinstance(result, Exception):
            raise result
        return result


class _KeepAliveConnectionPool:
    """Thread-safe pool of keep-alive HTTP connections."""

    def __init__(self, host: str, port: int, size: int, timeout: float,
                 ssl: bool = False, ssl_context=None,
                 acquire_timeout: Optional[float] = None):
        self._host = host
        self._port = port
        self._timeout = timeout
        self._ssl = ssl
        self._ssl_context = ssl_context
        self._idle: "queue.Queue" = queue.Queue()
        self._size = size
        self._created = 0
        self._lock = threading.Lock()
        # Bounded wait for an idle connection once the pool is at
        # capacity. An unbounded get() deadlocks the caller forever if
        # a connection ever leaks (e.g. a crashed worker that never
        # released) — fail loudly instead.
        self._acquire_timeout = acquire_timeout if acquire_timeout \
            else max(timeout, 1.0)

    def _new_connection(self):
        if self._ssl:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=self._timeout,
                context=self._ssl_context,
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout
        )

    def acquire(self):
        try:
            return self._idle.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            if self._created < self._size:
                self._created += 1
                return self._new_connection()
        try:
            return self._idle.get(timeout=self._acquire_timeout)
        except queue.Empty:
            raise InferenceServerException(
                "no idle connection became available within %.1fs "
                "(pool size %d, all in use — a connection may have "
                "leaked or every request is stuck); raise `concurrency`"
                " or investigate hung requests"
                % (self._acquire_timeout, self._size),
                status="UNAVAILABLE") from None

    def release(self, conn, broken: bool = False):
        if broken:
            try:
                conn.close()
            except Exception:
                pass
            conn = self._new_connection()
        self._idle.put(conn)

    def close(self):
        while True:
            try:
                conn = self._idle.get_nowait()
                conn.close()
            except queue.Empty:
                break


# Back-compat alias (pre-robustness name).
_ConnectionPool = _KeepAliveConnectionPool


class _HttpEndpoint:
    """One endpoint's transport: parsed address + keep-alive pool."""

    def __init__(self, url: str, ssl: bool, ssl_context, concurrency: int,
                 default_timeout: float, connection_timeout: float):
        self.url = url
        parsed = urlparse(url if "://" in url
                          else ("https://" if ssl else "http://") + url)
        if parsed.hostname is None:
            raise InferenceServerException("invalid url '%s'" % url)
        self.host = parsed.hostname
        self.port = parsed.port or (443 if ssl else 80)
        self.pool = _KeepAliveConnectionPool(
            self.host, self.port, max(concurrency, 1), default_timeout,
            ssl, ssl_context, acquire_timeout=connection_timeout,
        )


class InferenceServerClient(InferenceServerClientBase):
    """A client talking to one or more KServe-v2 HTTP/REST endpoints.

    ``concurrency`` sizes both the per-endpoint connection pool and the
    async worker pool (reference http/_client.py:178-188 semantics).

    ``retry_policy`` / ``circuit_breaker``
    (:mod:`client_tpu.robust`) make :meth:`infer` retry retryable
    failures (503/UNAVAILABLE, connection errors) with exponential
    backoff + full jitter, and fail fast while the breaker is open.

    ``url`` may be a comma-separated endpoint list (or a list), or an
    :class:`client_tpu.robust.EndpointPool` may be passed as
    ``endpoint_pool`` (possibly shared with other clients): ``infer``
    then routes least-outstanding across healthy endpoints, fails over
    on retryable errors, hedges tail-slow requests within the pool's
    budget, and a background prober readmits ejected endpoints. With a
    pool, ``circuit_breaker`` is ignored — health is per endpoint,
    owned by the pool.

    ``tracer`` (:class:`client_tpu.tracing.ClientTracer`) records a
    client-side span per ``infer`` and propagates its W3C
    ``traceparent`` header so the server's sampled span tree joins the
    client's trace; a caller-supplied ``traceparent`` in ``headers``
    wins over the generated one.
    """

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        concurrency: int = 1,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy=None,
        circuit_breaker=None,
        endpoint_pool=None,
        tracer=None,
    ):
        super().__init__()
        from client_tpu.robust import EndpointPool

        urls = (endpoint_pool.urls if endpoint_pool is not None
                else EndpointPool.split_url(url))
        if not urls:
            raise InferenceServerException("invalid url '%s'" % url)
        self._owns_pool = endpoint_pool is None and len(urls) > 1
        self._endpoint_pool = (endpoint_pool if endpoint_pool is not None
                               else (EndpointPool(urls) if len(urls) > 1
                                     else None))
        self._verbose = verbose
        self._default_timeout = max(connection_timeout, network_timeout)
        self._endpoints: Dict[str, _HttpEndpoint] = {
            u: _HttpEndpoint(u, ssl, ssl_context, concurrency,
                             self._default_timeout, connection_timeout)
            for u in urls
        }
        self._primary = self._endpoints[urls[0]]
        # Single-endpoint compat surface (tests and subclasses poke at
        # these; multi-endpoint callers should not).
        self._host = self._primary.host
        self._port = self._primary.port
        self._pool = self._primary.pool
        self._executor = ThreadPoolExecutor(max_workers=max(concurrency, 1))
        self._tracer = tracer
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker if self._endpoint_pool is None \
            else None
        self._closed = False
        if self._endpoint_pool is not None:
            from client_tpu.http._endpoints import probe_http_ready

            timeout = self._endpoint_pool.probe_timeout_s
            self._endpoint_pool.ensure_prober(
                lambda u, _ssl=ssl: probe_http_ready(u, timeout, _ssl))

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def pool_stats(self) -> Optional[dict]:
        """EndpointPool snapshot (hedges/failovers/ejections + per-
        endpoint health); None for a single-endpoint client."""
        return (self._endpoint_pool.stats()
                if self._endpoint_pool is not None else None)

    def close(self):
        if not self._closed:
            self._closed = True
            self._executor.shutdown(wait=True)
            for endpoint in self._endpoints.values():
                endpoint.pool.close()
            if self._endpoint_pool is not None and self._owns_pool:
                self._endpoint_pool.close()

    # -- low-level request -----------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[dict] = None,
        timeout: Optional[float] = None,
        endpoint: Optional[_HttpEndpoint] = None,
    ) -> Tuple[int, dict, bytes]:
        """``timeout`` caps THIS request's socket wait (per-call
        deadline); the pool's default timeout is restored on release.
        ``endpoint`` targets one fleet member (default: the primary)."""
        endpoint = endpoint or self._primary
        headers = self._call_plugin(dict(headers) if headers else {})
        conn = endpoint.pool.acquire()
        broken = False
        try:
            deadline = None
            if timeout is not None:
                deadline = time.monotonic() + timeout
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
            conn.request(method, path, body=body, headers=headers or {})
            response = conn.getresponse()
            if deadline is None:
                payload = response.read()
            else:
                # Absolute deadline, not per-socket-op: a server that
                # trickles one byte per (timeout) seconds would reset
                # a plain socket timeout forever. Re-arm the socket
                # with the REMAINING budget before every read.
                chunks = []
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise socket.timeout(
                            "deadline exhausted mid-response")
                    conn.sock.settimeout(remaining)
                    chunk = response.read1(65536)
                    if not chunk:
                        break
                    chunks.append(chunk)
                payload = b"".join(chunks)
            resp_headers = {k.lower(): v for k, v in response.getheaders()}
            if self._verbose:
                print("%s %s -> %d (%d bytes)"
                      % (method, path, response.status, len(payload)))
            return response.status, resp_headers, payload
        except (TimeoutError, socket.timeout) as e:
            # socket.timeout merged into TimeoutError only in py3.10;
            # naming both keeps py3.9 timeouts DEADLINE_EXCEEDED
            # instead of falling into the retryable-UNAVAILABLE branch.
            broken = True
            raise InferenceServerException(
                "request to %s:%d timed out after %.3fs"
                % (endpoint.host, endpoint.port,
                   timeout if timeout is not None else
                   self._default_timeout),
                status="DEADLINE_EXCEEDED",
            ) from e
        except (http.client.HTTPException, OSError) as e:
            broken = True
            raise InferenceServerException(
                "connection to %s:%d failed: %s"
                % (endpoint.host, endpoint.port, e),
                status="UNAVAILABLE",
            ) from e
        finally:
            if timeout is not None and not broken:
                conn.timeout = self._default_timeout
                if conn.sock is not None:
                    conn.sock.settimeout(self._default_timeout)
            endpoint.pool.release(conn, broken)

    def _get_json(self, path: str, headers=None, method: str = "GET",
                  body: Optional[bytes] = None):
        status, resp_headers, payload = self._request(method, path, body=body,
                                                      headers=headers)
        ep.raise_if_error(
            status, payload,
            retry_after_s=ep.parse_retry_after(
                resp_headers.get("retry-after")))
        return json.loads(payload) if payload else {}

    def _get_json_fleet(self, path: str, headers=None, method: str = "GET",
                        body: Optional[bytes] = None):
        """Run a control-plane verb against EVERY endpoint (shm
        registration, model load/unload): fleet members are replicas,
        so per-replica state must be applied to all of them. Single
        endpoint = plain call."""
        result = None
        for endpoint in self._endpoints.values():
            status, resp_headers, payload = self._request(
                method, path, body=body, headers=headers,
                endpoint=endpoint)
            ep.raise_if_error(
                status, payload,
                retry_after_s=ep.parse_retry_after(
                    resp_headers.get("retry-after")))
            result = json.loads(payload) if payload else {}
        return result

    # -- health / metadata ----------------------------------------------

    def is_server_live(self, headers=None, client_timeout=None) -> bool:
        """``client_timeout`` bounds the probe (gRPC-client parity) —
        a health check against a wedged server must not hang for the
        transport default."""
        status, _, _ = self._request("GET", "/v2/health/live",
                                     headers=headers, timeout=client_timeout)
        return status == 200

    def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        status, _, _ = self._request("GET", "/v2/health/ready",
                                     headers=headers, timeout=client_timeout)
        return status == 200

    def is_model_ready(self, model_name, model_version="", headers=None,
                       client_timeout=None) -> bool:
        status, _, _ = self._request(
            "GET", ep.ready_path(model_name, model_version), headers=headers,
            timeout=client_timeout,
        )
        return status == 200

    def get_server_metadata(self, headers=None) -> dict:
        return self._get_json("/v2", headers)

    def get_model_metadata(self, model_name, model_version="", headers=None
                           ) -> dict:
        return self._get_json(ep.model_path(model_name, model_version), headers)

    def get_model_config(self, model_name, model_version="", headers=None
                         ) -> dict:
        return self._get_json(ep.config_path(model_name, model_version), headers)

    def get_model_repository_index(self, headers=None) -> list:
        return self._get_json(ep.repo_index_path(), headers, method="POST",
                              body=b"{}")

    # -- model control ---------------------------------------------------

    def load_model(self, model_name, headers=None, config=None, files=None):
        self._get_json_fleet(ep.repo_load_path(model_name), headers,
                             method="POST", body=ep.load_model_body(config))

    def unload_model(self, model_name, headers=None, unload_dependents=False):
        self._get_json_fleet(ep.repo_unload_path(model_name), headers,
                             method="POST",
                             body=ep.unload_model_body(unload_dependents))

    # -- statistics / settings ------------------------------------------

    def get_inference_statistics(self, model_name="", model_version="",
                                 headers=None) -> dict:
        return self._get_json(ep.stats_path(model_name, model_version), headers)

    def update_trace_settings(self, model_name="", settings=None, headers=None
                              ) -> dict:
        return self._get_json(ep.trace_path(model_name), headers, method="POST",
                              body=json.dumps(settings or {}).encode())

    def get_trace_settings(self, model_name="", headers=None) -> dict:
        return self._get_json(ep.trace_path(model_name), headers)

    def update_log_settings(self, settings, headers=None) -> dict:
        return self._get_json(ep.logging_path(), headers, method="POST",
                              body=json.dumps(settings or {}).encode())

    def get_log_settings(self, headers=None) -> dict:
        return self._get_json(ep.logging_path(), headers)

    # -- shared memory ---------------------------------------------------

    def get_system_shared_memory_status(self, region_name="", headers=None
                                        ) -> list:
        return self._get_json(ep.shm_status_path("system", region_name),
                              headers)

    def register_system_shared_memory(self, name, key, byte_size, offset=0,
                                      headers=None):
        self._get_json_fleet(
            ep.shm_register_path("system", name), headers, method="POST",
            body=ep.system_shm_register_body(key, byte_size, offset),
        )

    def unregister_system_shared_memory(self, name="", headers=None):
        self._get_json_fleet(ep.shm_unregister_path("system", name), headers,
                             method="POST", body=b"{}")

    def get_tpu_shared_memory_status(self, region_name="", headers=None) -> list:
        return self._get_json(ep.shm_status_path("tpu", region_name), headers)

    def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                   byte_size, headers=None):
        """raw_handle: serialized TPU region descriptor (posted base64,
        the same shape the reference uses for cudaIpcMemHandle_t —
        http_client.cc:1712)."""
        self._get_json_fleet(
            ep.shm_register_path("tpu", name), headers, method="POST",
            body=ep.tpu_shm_register_body(raw_handle, device_id, byte_size),
        )

    def unregister_tpu_shared_memory(self, name="", headers=None):
        self._get_json_fleet(ep.shm_unregister_path("tpu", name), headers,
                             method="POST", body=b"{}")

    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- inference -------------------------------------------------------

    @staticmethod
    def generate_request_body(
        inputs: Sequence[InferInput],
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        parameters: Optional[dict] = None,
    ) -> Tuple[bytes, Optional[int]]:
        """Build an inference request body without sending it
        (reference http/_client.py:1219). Returns (body,
        json_header_length or None)."""
        return encode_infer_request(
            inputs=inputs, outputs=outputs, request_id=request_id,
            sequence_id=sequence_id, sequence_start=sequence_start,
            sequence_end=sequence_end, priority=priority, timeout=timeout,
            parameters=parameters,
        )

    @staticmethod
    def parse_response_body(
        response_body: bytes, header_length: Optional[int] = None
    ) -> InferResult:
        """Decode an inference response body obtained elsewhere
        (reference http/_client.py:1304)."""
        return InferResult.from_response_body(response_body, header_length)

    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        query_params: Optional[dict] = None,
        parameters: Optional[dict] = None,
        request_compression_algorithm: Optional[str] = None,
        response_compression_algorithm: Optional[str] = None,
    ) -> InferResult:
        """``request_compression_algorithm`` /
        ``response_compression_algorithm`` select per-call body
        compression ("gzip" or "deflate"; None = off), mirroring the
        reference HTTP client (http_client.cc:2130-2247). Response
        compression is a preference the server honors via
        Accept-Encoding.

        ``client_timeout`` (seconds) bounds this call end to end —
        gRPC-client parity. With a retry policy configured it is the
        TOTAL budget across attempts and backoffs, each attempt
        receiving the remainder; ``timeout`` (microseconds) remains the
        server-side queue deadline riding in the request parameters."""
        body, json_len = encode_infer_request(
            inputs=inputs, outputs=outputs, request_id=request_id,
            sequence_id=sequence_id, sequence_start=sequence_start,
            sequence_end=sequence_end, priority=priority, timeout=timeout,
            parameters=parameters,
        )
        request_headers = dict(headers) if headers else {}
        client_span = None
        if self._tracer is not None:
            client_span = self._tracer.start_span(
                "client_infer", model_name, request_id, request_headers)
            client_span.attrs["transport"] = "http"
            request_headers = client_span.inject(request_headers)
        if json_len is not None:
            request_headers[HEADER_LEN] = str(json_len)
            request_headers["Content-Type"] = "application/octet-stream"
        else:
            request_headers["Content-Type"] = "application/json"
        if request_compression_algorithm:
            body = compress_body(body, request_compression_algorithm)
            request_headers["Content-Encoding"] = \
                request_compression_algorithm
        if response_compression_algorithm:
            request_headers["Accept-Encoding"] = \
                response_compression_algorithm
        path = ep.infer_path(model_name, model_version)
        if query_params:
            path += "?" + "&".join(
                "%s=%s" % (quote(str(k)), quote(str(v)))
                for k, v in query_params.items()
            )

        def _decode(status, resp_headers, payload) -> InferResult:
            payload_out = decompress_body(
                payload, resp_headers.get("content-encoding"))
            ep.raise_if_error(
                status, payload_out,
                retry_after_s=ep.parse_retry_after(
                    resp_headers.get("retry-after")))
            response_header_len = resp_headers.get(HEADER_LEN.lower())
            return InferResult.from_response_body(
                payload_out,
                int(response_header_len) if response_header_len else None,
            )

        def _issue() -> InferResult:
            if self._endpoint_pool is not None:
                from client_tpu.robust import call_with_retry_pool

                def _pool_attempt(state, remaining) -> InferResult:
                    return _decode(*self._request(
                        "POST", path, body=body, headers=request_headers,
                        timeout=remaining,
                        endpoint=self._endpoints[state.url],
                    ))

                return call_with_retry_pool(
                    _pool_attempt, self._endpoint_pool, self._retry_policy,
                    deadline_s=client_timeout, sequence_id=sequence_id,
                    sequence_end=sequence_end,
                )

            def _attempt(remaining: Optional[float]) -> InferResult:
                return _decode(*self._request(
                    "POST", path, body=body, headers=request_headers,
                    timeout=remaining,
                ))

            from client_tpu.robust import call_with_retry

            return call_with_retry(
                _attempt, self._retry_policy, self._breaker,
                deadline_s=client_timeout,
            )

        if client_span is None:
            return _issue()
        try:
            result = _issue()
        except BaseException as e:
            client_span.finish(e)
            raise
        client_span.finish()
        return result

    def async_infer(self, model_name, inputs, **kwargs) -> InferAsyncRequest:
        """Run infer on the worker pool; returns a handle whose
        get_result() blocks for the InferResult."""

        def _work():
            try:
                return self.infer(model_name, inputs, **kwargs)
            except Exception as e:  # delivered via get_result
                return e

        return InferAsyncRequest(self._executor.submit(_work), self._verbose)
