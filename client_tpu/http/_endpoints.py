"""Shared URI construction + error mapping for the sync and asyncio
HTTP clients (single source of truth for the /v2 URI scheme)."""

from __future__ import annotations

import base64
import json
from urllib.parse import quote

from client_tpu import status_map
from client_tpu.utils import InferenceServerException


def model_path(model_name: str, model_version: str = "") -> str:
    path = "/v2/models/%s" % quote(model_name)
    if model_version:
        path += "/versions/%s" % model_version
    return path


def ready_path(model_name: str, model_version: str = "") -> str:
    return model_path(model_name, model_version) + "/ready"


def config_path(model_name: str, model_version: str = "") -> str:
    return model_path(model_name, model_version) + "/config"


def infer_path(model_name: str, model_version: str = "") -> str:
    return model_path(model_name, model_version) + "/infer"


def stats_path(model_name: str = "", model_version: str = "") -> str:
    if model_name:
        return model_path(model_name, model_version) + "/stats"
    return "/v2/models/stats"


def repo_index_path() -> str:
    return "/v2/repository/index"


def repo_load_path(model_name: str) -> str:
    return "/v2/repository/models/%s/load" % quote(model_name)


def repo_unload_path(model_name: str) -> str:
    return "/v2/repository/models/%s/unload" % quote(model_name)


def shm_status_path(kind: str, region_name: str = "") -> str:
    if region_name:
        return "/v2/%ssharedmemory/region/%s/status" % (kind, quote(region_name))
    return "/v2/%ssharedmemory/status" % kind


def shm_register_path(kind: str, region_name: str) -> str:
    return "/v2/%ssharedmemory/region/%s/register" % (kind, quote(region_name))


def shm_unregister_path(kind: str, region_name: str = "") -> str:
    if region_name:
        return "/v2/%ssharedmemory/region/%s/unregister" % (
            kind, quote(region_name),
        )
    return "/v2/%ssharedmemory/unregister" % kind


def trace_path(model_name: str = "") -> str:
    if model_name:
        return "/v2/models/%s/trace/setting" % quote(model_name)
    return "/v2/trace/setting"


def logging_path() -> str:
    return "/v2/logging"


def system_shm_register_body(key: str, byte_size: int, offset: int) -> bytes:
    return json.dumps(
        {"key": key, "offset": offset, "byte_size": byte_size}
    ).encode()


def tpu_shm_register_body(raw_handle: bytes, device_id: int,
                          byte_size: int) -> bytes:
    return json.dumps({
        "raw_handle": {"b64": base64.b64encode(raw_handle).decode()},
        "device_id": device_id,
        "byte_size": byte_size,
    }).encode()


def load_model_body(config=None) -> bytes:
    body: dict = {}
    if config is not None:
        body.setdefault("parameters", {})["config"] = config
    return json.dumps(body).encode()


def unload_model_body(unload_dependents: bool = False) -> bytes:
    return json.dumps(
        {"parameters": {"unload_dependents": unload_dependents}}
    ).encode()


def raise_if_error(status: int, body: bytes,
                   retry_after_s=None) -> None:
    if status < status_map.HTTP_ERROR_FLOOR:
        return
    try:
        message = json.loads(body).get("error", "")
    except Exception:
        message = body.decode(errors="replace")
    error = InferenceServerException(
        message or ("HTTP status %d" % status), status=str(status)
    )
    if retry_after_s is not None:
        # Server-advised backoff (Retry-After header, delta-seconds
        # form); RetryPolicy sleeps at least this long before retrying.
        error.retry_after_s = retry_after_s
    raise error


def parse_retry_after(value) -> "float | None":
    """Delta-seconds Retry-After header value -> seconds (HTTP-date
    forms are ignored: the servers here only send delta-seconds)."""
    if not value:
        return None
    try:
        seconds = float(value)
    except (TypeError, ValueError):
        return None
    return seconds if seconds > 0 else None


def probe_http_ready(url: str, timeout: float = 1.0,
                     ssl: bool = False) -> bool:
    """Bounded stdlib /v2/health/ready probe for one endpoint — the
    EndpointPool prober's health check. Self-contained (no client
    connection pool) so a wedged pool can never block probing, and
    usable from asyncio clients without touching their event loop."""
    import http.client
    from urllib.parse import urlparse

    if "://" not in url:
        url = ("https://" if ssl else "http://") + url
    parsed = urlparse(url)
    if parsed.hostname is None:
        return False
    conn_cls = (http.client.HTTPSConnection if parsed.scheme == "https"
                else http.client.HTTPConnection)
    conn = conn_cls(parsed.hostname,
                    parsed.port or (443 if parsed.scheme == "https" else 80),
                    timeout=timeout)
    try:
        conn.request("GET", "/v2/health/ready")
        return conn.getresponse().status == 200
    except Exception:  # noqa: BLE001 — any failure = not ready
        return False
    finally:
        conn.close()
