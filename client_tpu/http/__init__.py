"""KServe-v2 HTTP/REST client (sync + callback-async, binary tensor
protocol). ``client_tpu.http.aio`` holds the asyncio mirror."""

from client_tpu._infer_common import InferInput, InferRequestedOutput  # noqa: F401
from client_tpu._plugin import (  # noqa: F401
    BasicAuth,
    InferenceServerClientPlugin,
    Request,
)
from client_tpu.http._client import (  # noqa: F401
    InferAsyncRequest,
    InferenceServerClient,
    InferResult,
)
from client_tpu.robust import CircuitBreaker, RetryPolicy  # noqa: F401
from client_tpu.utils import InferenceServerException  # noqa: F401
