"""Ring attention: exact attention over a sequence-sharded axis.

Long-context forward passes shard the sequence over the mesh's ``sp``
axis. Plain attention would force XLA to all-gather the full K/V
(memory O(S_global)); ring attention instead rotates K/V shards around
the ring with ``lax.ppermute`` — P steps, each attending the local Q
block to one remote K/V block — while accumulating a numerically
stable streaming softmax (the log-sum-exp trick flash attention uses).
Peak memory stays O(S_local) per device and every hop rides the ring's
ICI neighbour links, never DCN.

The reference client has no model parallelism anywhere in its tree
(SURVEY.md §2.7) — this op exists for the framework's own long-context
models (models/llm.py forward/training path), not as a port.

Algorithm: Liu et al., "Ring Attention with Blockwise Transformers for
Near-Infinite Context" (arXiv:2310.01889) — re-derived here for
jax shard_map; no reference implementation was consulted.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exports shard_map at the top level
    _shard_map = jax.shard_map
except AttributeError:  # older releases keep it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: Optional[float], vary_axes: tuple):
    """Per-device body (runs under shard_map). q/k/v: [B, S_loc, H, D]
    local shards of a [B, S_loc*P, H, D] global array; returns the
    local [B, S_loc, H, D] output shard."""
    p = lax.psum(1, axis_name)
    my_block = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    # Work in [B, H, S, D]; accumulate in f32 regardless of input dtype.
    qh = q.transpose(0, 2, 1, 3).astype(jnp.float32) * scale
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)

    # The accumulators become device-varying from step 0 (the K/V
    # they absorb differ per device), so the scan carry type is
    # consistent under shard_map's varying-axes check. pcast replaced
    # pvary in newer jax; keep the fallback for older releases.
    if hasattr(lax, "pcast"):
        def _vary(x):
            return lax.pcast(x, vary_axes, to="varying")
    elif hasattr(lax, "pvary"):
        def _vary(x):
            return lax.pvary(x, vary_axes)
    else:
        # jax 0.4.x: the shard_map rep-checker inserts replicated->
        # varying conversions itself; no explicit marker op exists
        # (lax.pbroadcast there is a real collective, not the marker).
        def _vary(x):
            return x
    out = _vary(jnp.zeros((b, h, s, d), jnp.float32))
    row_max = _vary(jnp.full((b, h, s), -jnp.inf, jnp.float32))
    row_sum = _vary(jnp.zeros((b, h, s), jnp.float32))
    perm = [(j, (j + 1) % p) for j in range(p)]

    def step(carry, i):
        out, row_max, row_sum, kh, vh = carry
        # After i rotations this device holds the K/V block that
        # started on device (my_block - i) mod p.
        src_block = (my_block - i) % p
        logits = jnp.einsum(
            "bhsd,bhtd->bhst", qh, kh.astype(jnp.float32))
        if causal:
            q_pos = my_block * s + jnp.arange(s)
            k_pos = src_block * s + jnp.arange(s)
            visible = (q_pos[:, None] >= k_pos[None, :]).astype(
                jnp.float32)
        else:
            visible = jnp.ones((s, s), jnp.float32)
        # Streaming softmax: rescale the running numerator/denominator
        # by exp(old_max - new_max), add this block's contribution.
        # Masked entries are zeroed explicitly (not -inf) so a block
        # with no visible keys contributes exactly nothing.
        block_max = jnp.max(
            jnp.where(visible > 0, logits, -jnp.inf), axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        # Fully-masked-so-far rows keep -inf; use a finite stand-in for
        # the subtraction (their weights are zeroed by `visible`).
        safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
        alpha = jnp.where(
            jnp.isfinite(row_max),
            jnp.exp(row_max - safe_max), 0.0)
        # Gate the exp itself, not just the product: a masked (future)
        # logit can exceed the visible-only max by enough to overflow
        # exp() to inf, and inf * 0 = NaN.
        weights = jnp.where(
            visible > 0, jnp.exp(logits - safe_max[..., None]), 0.0)
        row_sum = row_sum * alpha + jnp.sum(weights, axis=-1)
        out = out * alpha[..., None] + jnp.einsum(
            "bhst,bhtd->bhsd", weights, vh.astype(jnp.float32))
        kh = lax.ppermute(kh, axis_name, perm)
        vh = lax.ppermute(vh, axis_name, perm)
        return (out, new_max, row_sum, kh, vh), None

    (out, _, row_sum, _, _), _ = lax.scan(
        step, (out, row_max, row_sum, kh, vh), jnp.arange(p))
    out = out / jnp.maximum(row_sum, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sp",
                   causal: bool = True, scale: Optional[float] = None,
                   batch_axis: Optional[str] = "dp"):
    """Exact attention with q/k/v sequence-sharded over
    ``axis_name``. q/k/v: [B, S, H, D] global arrays (S divisible by
    the axis size); returns [B, S, H, D] with the same sharding.
    ``batch_axis`` additionally shards batch when present in the mesh.
    """
    db = batch_axis if (batch_axis and batch_axis in mesh.axis_names) \
        else None
    spec = P(db, axis_name, None, None)
    vary_axes = (axis_name,) + ((db,) if db else ())
    local = partial(_ring_attention_local, axis_name=axis_name,
                    causal=causal, scale=scale, vary_axes=vary_axes)
    fn = _shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    constraint = NamedSharding(mesh, spec)
    q, k, v = (lax.with_sharding_constraint(x, constraint)
               for x in (q, k, v))
    return fn(q, k, v)
