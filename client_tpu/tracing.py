"""Client-side trace context: W3C traceparent propagation + a
lightweight client span recorder.

The client half of the end-to-end span story (docs/tracing.md): every
client (HTTP/gRPC x sync/aio) can carry a :class:`ClientTracer`; each
``infer`` then either adopts a caller-supplied ``traceparent`` header
or mints one, records a client-side send/receive span, and ships the
context to the server as the standard W3C ``traceparent`` HTTP header
/ gRPC metadata entry. A server whose sampler picks the request up
joins the SAME trace id, with the client span as the server root
span's parent — one tree across the wire.

Kept dependency-free and transport-neutral so both the clients and
the server's span recorder (client_tpu.server.tracing) share one
definition of the wire format.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

TRACEPARENT_HEADER = "traceparent"

# W3C trace-context version we emit; '01' flags = sampled.
_VERSION = "00"
_SAMPLED = "01"


# Ids come from a PRNG seeded once from the OS: os.urandom costs ~10us
# per call on older kernels, and a sampled request mints 8+ ids — the
# syscall alone would dominate the span recorder's budget. Trace/span
# ids need uniqueness, not cryptographic strength. random.getrandbits
# is a single C call (atomic under the GIL), so this is thread-safe.
_rng = __import__("random").Random(int.from_bytes(os.urandom(8), "big"))


def new_trace_id() -> str:
    """128-bit random trace id, 32 lowercase hex chars."""
    return "%032x" % _rng.getrandbits(128)


def new_span_id() -> str:
    """64-bit random span id, 16 lowercase hex chars (never zero —
    the W3C all-zero parent id means 'absent')."""
    return "%016x" % (_rng.getrandbits(64) | 1)


def format_traceparent(trace_id: str, span_id: str) -> str:
    """``00-<trace-id>-<parent-id>-01`` (always flagged sampled; the
    server applies its own trace_rate on top)."""
    return "-".join((_VERSION, trace_id, span_id, _SAMPLED))


def parse_traceparent(value: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a traceparent header, or None
    when absent/malformed (a bad header must never fail a request)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    _version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return trace_id, span_id


class ClientSpan:
    """One client-side send/receive span. Use as a context manager or
    call :meth:`finish` explicitly; the span is recorded either way."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "start_ns",
                 "end_ns", "attrs", "_done")

    def __init__(self, tracer: "ClientTracer", name: str, trace_id: str,
                 span_id: str, attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.start_ns = time.monotonic_ns()
        self.end_ns = 0
        self.attrs = dict(attrs) if attrs else {}
        self._done = False

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def inject(self, headers: Optional[dict]) -> dict:
        """Returns ``headers`` (a new dict when None) with this span's
        traceparent set — UNLESS the caller already supplied one (the
        caller's context wins; this span then joins that trace)."""
        headers = dict(headers) if headers else {}
        existing = parse_traceparent(headers.get(TRACEPARENT_HEADER))
        if existing is not None:
            self.trace_id, _parent = existing
        else:
            headers[TRACEPARENT_HEADER] = self.traceparent
        return headers

    def finish(self, error: Optional[BaseException] = None) -> None:
        if self._done:
            return
        self._done = True
        self.end_ns = time.monotonic_ns()
        if error is not None:
            self.attrs["error"] = str(error)
        self.tracer._record(self)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.finish(exc)
        return False


class ClientTracer:
    """Thread-safe recorder of client-side spans.

    ``path``, when set, appends one JSON line per span on
    :meth:`flush` (same compact shape as the server's span records, so
    client and server lines can be joined on ``trace_id``) — and spans
    auto-flush there every ``flush_every`` records, so a long-lived
    traced client never grows without bound. Without a path the
    buffer is a ring capped at ``max_records`` (oldest spans drop):
    an unconsumed tracer must not become a memory leak.
    """

    def __init__(self, path: Optional[str] = None,
                 max_records: int = 10_000, flush_every: int = 100):
        self.path = path
        self._max_records = max(int(max_records), 1)
        self._flush_every = max(int(flush_every), 1)
        self._lock = threading.Lock()
        self._spans: List[ClientSpan] = []

    def start_span(self, name: str, model_name: str = "",
                   request_id: str = "",
                   headers: Optional[dict] = None) -> ClientSpan:
        """Starts a client span, adopting a caller-supplied
        traceparent from ``headers`` when present."""
        existing = parse_traceparent(
            (headers or {}).get(TRACEPARENT_HEADER))
        trace_id = existing[0] if existing else new_trace_id()
        attrs = {}
        if model_name:
            attrs["model"] = model_name
        if request_id:
            attrs["request_id"] = request_id
        return ClientSpan(self, name, trace_id, new_span_id(), attrs)

    def _record(self, span: ClientSpan) -> None:
        flush_now = False
        with self._lock:
            self._spans.append(span)
            if self.path:
                flush_now = len(self._spans) >= self._flush_every
            elif len(self._spans) > self._max_records:
                del self._spans[:len(self._spans) - self._max_records]
        if flush_now:
            try:
                self.flush()
            except OSError:
                pass  # tracing must never fail the request path

    def records(self) -> List[dict]:
        """Snapshot of recorded spans as JSON-able dicts."""
        with self._lock:
            spans = list(self._spans)
        return [
            {
                "name": s.name,
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "start_ns": s.start_ns,
                "end_ns": s.end_ns,
                "attrs": dict(s.attrs),
            }
            for s in spans
        ]

    def flush(self) -> int:
        """Appends recorded spans to ``path`` as JSON lines and clears
        the buffer; returns the number written (0 with no path)."""
        import json

        records = self.records()
        with self._lock:
            self._spans = []
        if not self.path or not records:
            return 0
        with open(self.path, "a") as f:
            for record in records:
                f.write(json.dumps(record) + "\n")
        return len(records)
