"""Result export: console table, JSON, CSV (parity: genai-perf
export/console exporters)."""

from __future__ import annotations

import csv
import json
from typing import List, Optional

from client_tpu.genai.metrics import Statistics

_COLUMNS = ["mean", "min", "max", "p99", "p95", "p90", "p75", "p50", "p25"]


def console_report(stats: Statistics, title: str = "LLM Metrics") -> str:
    lines = ["", title, "=" * len(title)]
    header = "%-28s" % "Statistic" + "".join(
        "%12s" % c for c in _COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))
    for name, entry in stats.as_dict().items():
        if "value" in entry:
            continue
        # Server-side rows (bucket-quantile estimates from /metrics)
        # only carry mean/p50/p99 — blank cells beat printing NaN.
        lines.append("%-28s" % name + "".join(
            ("%12.2f" % entry[c]) if c in entry else "%12s" % "-"
            for c in _COLUMNS))
    for name, entry in stats.as_dict().items():
        if "value" in entry:
            lines.append("%-28s%12.2f" % (name, entry["value"]))
    return "\n".join(lines)


def export_json(stats_list: List[Statistics], path: str,
                meta: Optional[dict] = None) -> None:
    doc = {
        "meta": meta or {},
        "experiments": [s.as_dict() for s in stats_list],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)


def export_parquet(stats_list: List[Statistics], path: str) -> None:
    """Raw per-request samples as a long-format parquet table
    (experiment, metric, sample_index, value) — parity: genai-perf's
    parquet export of the raw profile dataframe."""
    import pandas as pd

    rows = []
    for idx, stats in enumerate(stats_list):
        for name, samples in stats.metrics.data().items():
            for i, value in enumerate(samples):
                rows.append((idx, name, i, float(value)))
        rows.append((idx, "request_throughput_per_s", 0,
                     stats.metrics.request_throughput_per_s))
        rows.append((idx, "output_token_throughput_per_s", 0,
                     stats.metrics.output_token_throughput_per_s))
    frame = pd.DataFrame(
        rows, columns=["experiment", "metric", "sample_index", "value"])
    frame.to_parquet(path, index=False)


def export_csv(stats_list: List[Statistics], path: str) -> None:
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(["experiment", "metric"] + _COLUMNS + ["value"])
        for idx, stats in enumerate(stats_list):
            for name, entry in stats.as_dict().items():
                writer.writerow(
                    [idx, name]
                    + [round(entry[c], 4) if c in entry else ""
                       for c in _COLUMNS]
                    + [round(entry["value"], 4) if "value" in entry else ""]
                )
