"""LLM metrics from the perf profile export (parity: genai-perf
llm_metrics.py:45-254 — LLMProfileDataParser / LLMMetrics /
Statistics).

The profile export (client_tpu.perf.report.export_profile) records one
``timestamp`` and a list of ``response_timestamps`` per request; with
the decoupled generate model every streamed response carries one
token, so response counts double as output token counts unless
response texts are present for a tokenizer to count."""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

NANOS = 1_000_000_000


class LLMMetrics:
    """Raw per-request series for one experiment (load level)."""

    def __init__(
        self,
        time_to_first_token_ns: List[int],
        inter_token_latency_ns: List[int],
        request_latency_ns: List[int],
        output_token_counts: List[int],
        benchmark_duration_s: float,
        itl_sequences_ns: List[List[int]] = None,
    ):
        self.time_to_first_token_ns = time_to_first_token_ns
        self.inter_token_latency_ns = inter_token_latency_ns
        self.request_latency_ns = request_latency_ns
        self.output_token_counts = output_token_counts
        self.benchmark_duration_s = benchmark_duration_s
        # Per-request gap sequences (token position preserved) — the
        # token-position heatmap's input; the flat series above cannot
        # reconstruct position.
        self.itl_sequences_ns = itl_sequences_ns or []

    @property
    def request_throughput_per_s(self) -> float:
        if self.benchmark_duration_s <= 0:
            return 0.0
        return len(self.request_latency_ns) / self.benchmark_duration_s

    @property
    def output_token_throughput_per_s(self) -> float:
        if self.benchmark_duration_s <= 0:
            return 0.0
        return sum(self.output_token_counts) / self.benchmark_duration_s

    def data(self) -> Dict[str, List[float]]:
        """Metric name -> samples (ns series reported in ms)."""
        return {
            "time_to_first_token_ms": [
                t / 1e6 for t in self.time_to_first_token_ns],
            "inter_token_latency_ms": [
                t / 1e6 for t in self.inter_token_latency_ns],
            "request_latency_ms": [
                t / 1e6 for t in self.request_latency_ns],
            "output_token_count": list(map(float,
                                           self.output_token_counts)),
        }


_PERCENTILES = (25, 50, 75, 90, 95, 99)


class Statistics:
    """mean/std/min/max/p25..p99 for every metric plus the throughput
    scalars (parity: genai-perf Statistics)."""

    def __init__(self, metrics: LLMMetrics):
        self._metrics = metrics
        self.stats: Dict[str, Dict[str, float]] = {}
        for name, samples in metrics.data().items():
            if not samples:
                continue
            arr = np.array(samples, dtype=np.float64)
            entry = {
                "mean": float(arr.mean()),
                "std": float(arr.std()),
                "min": float(arr.min()),
                "max": float(arr.max()),
            }
            for p in _PERCENTILES:
                entry["p%d" % p] = float(np.percentile(arr, p))
            self.stats[name] = entry
        self.stats["request_throughput_per_s"] = {
            "value": metrics.request_throughput_per_s}
        self.stats["output_token_throughput_per_s"] = {
            "value": metrics.output_token_throughput_per_s}

    @property
    def metrics(self) -> LLMMetrics:
        return self._metrics

    def as_dict(self) -> Dict[str, Dict[str, float]]:
        return self.stats


class LLMProfileDataParser:
    """Reads the profile-export JSON and derives LLM metrics per
    experiment (parity: LLMProfileDataParser llm_metrics.py)."""

    def __init__(self, filename: str = None, tokenizer=None,
                 document: Optional[dict] = None):
        if document is None:
            with open(filename) as f:
                document = json.load(f)
        self._doc = document
        self._tokenizer = tokenizer
        self.experiments: List[dict] = self._doc.get("experiments", [])

    def get_statistics(self, experiment_index: int = 0) -> Statistics:
        return Statistics(self.get_metrics(experiment_index))

    def get_metrics(self, experiment_index: int = 0) -> LLMMetrics:
        exp = self.experiments[experiment_index]
        requests = exp.get("requests", [])
        ttft, latency, token_counts = [], [], []
        min_start, max_end = None, None
        itl_sequences = []
        for req in requests:
            start = req["timestamp"]
            responses = sorted(req.get("response_timestamps", []))
            if not responses:
                continue
            ttft.append(responses[0] - start)
            gaps = [b - a for a, b in zip(responses, responses[1:])]
            if gaps:
                itl_sequences.append(gaps)
            latency.append(responses[-1] - start)
            token_counts.append(self._token_count(req, responses))
            min_start = start if min_start is None else min(min_start, start)
            max_end = (responses[-1] if max_end is None
                       else max(max_end, responses[-1]))
        # The flat series is DERIVED from the sequences — one source
        # of truth, so stats and the token-position heatmap can never
        # disagree.
        itl = [gap for seq in itl_sequences for gap in seq]
        duration_s = (
            (max_end - min_start) / NANOS
            if min_start is not None and max_end > min_start else 0.0
        )
        return LLMMetrics(ttft, itl, latency, token_counts, duration_s,
                          itl_sequences_ns=itl_sequences)

    def _token_count(self, req: dict, responses: List[int]) -> int:
        texts = req.get("response_texts")
        if texts and self._tokenizer is not None:
            return len(self._tokenizer.encode("".join(texts)))
        # decoupled generate: one token per streamed response
        return len(responses)


# -- server-side telemetry join -------------------------------------------
#
# The server exposes always-on TTFT/ITL histograms on /metrics
# (client_tpu.server.telemetry). Scraping the endpoint before and after
# the run and differencing the cumulative buckets yields the RUN's
# server-observed distributions — printed beside the client-observed
# numbers above, the queueing-vs-network decomposition a client-only
# genai-perf cannot do (client TTFT - server TTFT ~= network + client
# stack time).

# (histogram attr on the scrape, stats row name) — values land in ms
# to match the client-side rows.
_SERVER_METRIC_ROWS = (
    ("stream_first_response_us", "server_time_to_first_token_ms"),
    ("stream_inter_response_us", "server_inter_token_latency_ms"),
    ("request_duration_us", "server_request_latency_ms"),
)


def fetch_metrics_text(url: str, timeout_s: float = 5.0) -> str:
    """One raw scrape of a Prometheus /metrics endpoint (the URL may
    omit the scheme and /metrics path — MetricsManager owns the
    normalization rules, one copy for both harnesses)."""
    from client_tpu.perf.metrics_manager import MetricsManager

    return MetricsManager(url, timeout_s=timeout_s).scrape_text()


def parse_server_histograms(before_text: str, after_text: str,
                            model_name: str
                            ) -> Dict[str, Dict[str, float]]:
    """Server-observed TTFT / ITL / request-latency stats for
    ``model_name`` from two scrapes bracketing the run: bucket deltas
    give the run's distribution, quantiles are estimated by linear
    interpolation inside the containing bucket. Returns stats rows
    (``{"mean"/"p50"/"p99": ms}``) to merge into Statistics.stats;
    empty when the model streamed nothing between the scrapes."""
    from client_tpu.perf.metrics_manager import (
        histogram_quantiles,
        parse_prometheus,
        summarize_metrics,
    )

    snapshots = [parse_prometheus(before_text),
                 parse_prometheus(after_text)]
    quantiles = histogram_quantiles(summarize_metrics(snapshots))
    out: Dict[str, Dict[str, float]] = {}
    for attr, row_name in _SERVER_METRIC_ROWS:
        entry = quantiles.get("%s|%s" % (attr, model_name))
        if entry:
            out[row_name] = {
                "mean": entry["mean_us"] / 1000.0,
                "p50": entry["p50_us"] / 1000.0,
                "p99": entry["p99_us"] / 1000.0,
            }
    return out
