"""Interactive benchmark report: one self-contained HTML file.

Parity: the reference's genai-perf emits interactive plotly HTML
(reference src/c++/perf_analyzer/genai-perf/genai_perf/plots/ —
BasePlot subclasses call plotly `fig.write_html`). Plotly is not on
this image, so the report is hand-rendered SVG + a small vanilla-JS
hover layer — no network, no dependencies, one file that opens
anywhere.

Chart set mirrors plots.py's static PNGs: stat tiles (the headline
numbers), TTFT-per-request scatter, request-latency histogram,
inter-token-latency box summary, and the token-position heatmap.
Every mark carries a hover tooltip; a table view of the summary
statistics ships in the same file.
"""

from __future__ import annotations

import html
import json
import os
from typing import List

from client_tpu.genai.metrics import Statistics

# Categorical slots 1-3 (light, dark): the all-pairs-validated prefix
# of the reference palette; experiments beyond three fold into the
# table view rather than minting new hues.
SERIES_LIGHT = ["#2a78d6", "#eb6834", "#1baf7a"]
SERIES_DARK = ["#3987e5", "#d95926", "#199e70"]
MAX_SERIES = 3

# Sequential single-hue ramp (blue, light->dark) for the heatmap.
SEQ_RAMP = ["#eaf2fc", "#c4dbf5", "#9cc2ec", "#6fa4e2",
            "#4485d9", "#2a6ab8", "#1b4a85"]

_CSS = """
.viz-root {
  color-scheme: light;
  --surface-1: #fcfcfb; --surface-2: #f2f1ee;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #e4e3df; --axis: #b9b8b2;
  font: 14px/1.45 system-ui, sans-serif;
  background: var(--surface-1); color: var(--text-primary);
  max-width: 980px; margin: 0 auto; padding: 24px;
}
@media (prefers-color-scheme: dark) {
  :root:where(:not([data-theme="light"])) .viz-root {
    color-scheme: dark;
    --surface-1: #1a1a19; --surface-2: #242423;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --grid: #333330; --axis: #55544f;
  }
}
.viz-root h1 { font-size: 20px; margin: 0 0 4px; }
.viz-root .sub { color: var(--text-secondary); margin: 0 0 20px; }
.tiles { display: flex; gap: 12px; flex-wrap: wrap; margin: 0 0 24px; }
.tile { background: var(--surface-2); border-radius: 8px;
        padding: 12px 16px; min-width: 130px; }
.tile .v { font-size: 22px; font-weight: 600; }
.tile .l { color: var(--text-secondary); font-size: 12px; }
.chart { margin: 0 0 28px; }
.chart h2 { font-size: 15px; margin: 0 0 2px; }
.chart .d { color: var(--text-secondary); font-size: 12px; margin: 0 0 8px; }
.legend { display: flex; gap: 14px; font-size: 12px;
          color: var(--text-secondary); margin: 4px 0 6px; }
.legend .sw { display: inline-block; width: 10px; height: 10px;
              border-radius: 3px; margin-right: 5px; }
svg text { fill: var(--text-secondary); font-size: 11px; }
svg .axisline { stroke: var(--axis); stroke-width: 1; }
svg .gridline { stroke: var(--grid); stroke-width: 1; }
#tip { position: fixed; pointer-events: none; display: none;
       background: var(--text-primary); color: var(--surface-1);
       padding: 4px 8px; border-radius: 5px; font-size: 12px; z-index: 9; }
table.stats { border-collapse: collapse; font-size: 13px; }
table.stats th, table.stats td { padding: 4px 10px; text-align: right;
  border-bottom: 1px solid var(--grid); }
table.stats th:first-child, table.stats td:first-child { text-align: left; }
details { margin: 0 0 24px; }
details summary { cursor: pointer; color: var(--text-secondary); }
"""

_JS = """
(function () {
  var tip = document.getElementById('tip');
  document.querySelectorAll('[data-tip]').forEach(function (el) {
    el.addEventListener('mousemove', function (ev) {
      tip.textContent = el.getAttribute('data-tip');
      tip.style.display = 'block';
      tip.style.left = (ev.clientX + 12) + 'px';
      tip.style.top = (ev.clientY - 10) + 'px';
    });
    el.addEventListener('mouseleave', function () {
      tip.style.display = 'none';
    });
  });
})();
"""


def _fmt(value: float) -> str:
    if value >= 100:
        return "%.0f" % value
    if value >= 1:
        return "%.1f" % value
    return "%.3g" % value


def _scale(lo: float, hi: float, out_lo: float, out_hi: float):
    span = (hi - lo) or 1.0

    def to(v: float) -> float:
        return out_lo + (v - lo) / span * (out_hi - out_lo)

    return to


def _axes(width, height, pad, y_lo, y_hi, x_label, y_label):
    """Recessive grid + axis lines + 4 y-ticks."""
    parts = []
    ty = _scale(y_lo, y_hi, height - pad, pad)
    for i in range(5):
        v = y_lo + (y_hi - y_lo) * i / 4
        y = ty(v)
        parts.append('<line class="gridline" x1="%d" y1="%.1f" x2="%d" '
                     'y2="%.1f"/>' % (pad, y, width - 8, y))
        parts.append('<text x="%d" y="%.1f" text-anchor="end">%s</text>'
                     % (pad - 6, y + 4, _fmt(v)))
    parts.append('<line class="axisline" x1="%d" y1="%d" x2="%d" y2="%d"/>'
                 % (pad, height - pad, width - 8, height - pad))
    parts.append('<text x="%d" y="%d" text-anchor="middle">%s</text>'
                 % ((width + pad) // 2, height - 4, html.escape(x_label)))
    parts.append('<text x="12" y="%d" transform="rotate(-90 12 %d)" '
                 'text-anchor="middle">%s</text>'
                 % (height // 2, height // 2, html.escape(y_label)))
    return "".join(parts), ty


def _legend(n: int) -> str:
    if n < 2:
        return ""
    items = "".join(
        '<span><span class="sw" style="background:var(--s%d)"></span>'
        'experiment %d</span>' % (i, i) for i in range(min(n, MAX_SERIES)))
    more = ('<span>(+%d more in the table)</span>' % (n - MAX_SERIES)
            if n > MAX_SERIES else "")
    return '<div class="legend">%s%s</div>' % (items, more)


def _series_vars() -> str:
    light = "".join("--s%d: %s; " % (i, c)
                    for i, c in enumerate(SERIES_LIGHT))
    dark = "".join("--s%d: %s; " % (i, c) for i, c in enumerate(SERIES_DARK))
    return (".viz-root { %s}\n"
            "@media (prefers-color-scheme: dark) {\n"
            "  :root:where(:not([data-theme=\"light\"])) .viz-root { %s}\n"
            "}\n" % (light, dark))


def _scatter(data_list, n_experiments: int) -> str:
    """TTFT per request: per-mark hover, >=8px targets."""
    series = [d.get("time_to_first_token_ms", [])
              for d in data_list[:MAX_SERIES]]
    points = [(i, j, v) for i, samples in enumerate(series)
              for j, v in enumerate(samples)]
    if not points:
        return ""
    width, height, pad = 920, 260, 58
    y_hi = max(v for _, _, v in points) * 1.08
    x_hi = max(max((len(s) for s in series)) - 1, 1)
    grid, ty = _axes(width, height, pad, 0.0, y_hi,
                     "request index", "TTFT (ms)")
    tx = _scale(0, x_hi, pad + 8, width - 20)
    marks = "".join(
        '<circle cx="%.1f" cy="%.1f" r="4.5" fill="var(--s%d)" '
        'data-tip="exp %d · request %d · %s ms"/>'
        % (tx(j), ty(v), i, i, j, _fmt(v)) for i, j, v in points)
    return ('<div class="chart"><h2>Time to first token</h2>'
            '<p class="d">one mark per request, in arrival order</p>%s'
            '<svg viewBox="0 0 %d %d" width="100%%">%s%s</svg></div>'
            % (_legend(n_experiments), width, height, grid, marks))


def _histogram(data_list, n_experiments: int) -> str:
    series = [d.get("request_latency_ms", [])
              for d in data_list[:MAX_SERIES]]
    merged = [v for s in series for v in s]
    if not merged:
        return ""
    lo, hi = min(merged), max(merged) * 1.0001
    bins = min(24, max(5, len(merged) // 2))
    step = (hi - lo) / bins or 1.0
    counts = [[0] * bins for _ in series]
    for i, samples in enumerate(series):
        for v in samples:
            counts[i][min(int((v - lo) / step), bins - 1)] += 1
    width, height, pad = 920, 240, 58
    y_hi = max(max(c) for c in counts) * 1.1 or 1
    grid, ty = _axes(width, height, pad, 0, y_hi,
                     "request latency (ms)", "requests")
    plot_w = width - 28 - pad
    group_w = plot_w / bins
    bar_w = max((group_w - 2 * len(series)) / max(len(series), 1), 2)
    bars = []
    for i, row in enumerate(counts):
        for b, count in enumerate(row):
            if not count:
                continue
            x = pad + 8 + b * group_w + i * (bar_w + 2)
            y = ty(count)
            bars.append(
                '<rect x="%.1f" y="%.1f" width="%.1f" height="%.1f" '
                'rx="2" fill="var(--s%d)" data-tip='
                '"exp %d · %s-%s ms · %d requests"/>'
                % (x, y, bar_w, (height - pad) - y, i, i,
                   _fmt(lo + b * step), _fmt(lo + (b + 1) * step), count))
    return ('<div class="chart"><h2>Request latency</h2>'
            '<p class="d">distribution across all requests</p>%s'
            '<svg viewBox="0 0 %d %d" width="100%%">%s%s</svg></div>'
            % (_legend(n_experiments), width, height, grid, "".join(bars)))


def _boxes(stats_list) -> str:
    """ITL five-number summaries as thin boxes with whiskers — from
    Statistics' own percentile table (one interpolation convention:
    metrics.py computes it, every view reuses it). Series slots keep
    their original experiment index even when a non-streaming
    experiment has no ITL samples (color follows the entity)."""
    boxes = []  # (experiment index, stats entry)
    for i, stats in enumerate(stats_list[:MAX_SERIES]):
        entry = stats.stats.get("inter_token_latency_ms")
        if entry:
            boxes.append((i, entry))
    if not boxes:
        return ""
    width, height, pad = 920, 220, 58
    y_hi = max(entry["max"] for _, entry in boxes) * 1.1
    grid, ty = _axes(width, height, pad, 0.0, y_hi,
                     "experiment", "inter-token latency (ms)")
    plot_w = width - 28 - pad
    marks = []
    for slot, (i, entry) in enumerate(boxes):
        q1, med, q3 = entry["p25"], entry["p50"], entry["p75"]
        center = pad + 8 + plot_w * (slot + 0.5) / len(boxes)
        half = 28
        tip = ("exp %d · min %s · p25 %s · median %s · p75 %s · max %s ms"
               % (i, _fmt(entry["min"]), _fmt(q1), _fmt(med), _fmt(q3),
                  _fmt(entry["max"])))
        marks.append(
            '<g data-tip="%s">'
            '<line x1="%.1f" y1="%.1f" x2="%.1f" y2="%.1f" '
            'stroke="var(--s%d)" stroke-width="2"/>'
            '<rect x="%.1f" y="%.1f" width="%d" height="%.1f" rx="4" '
            'fill="var(--s%d)" fill-opacity="0.35" stroke="var(--s%d)" '
            'stroke-width="2"/>'
            '<line x1="%.1f" y1="%.1f" x2="%.1f" y2="%.1f" '
            'stroke="var(--s%d)" stroke-width="2"/></g>'
            % (html.escape(tip),
               center, ty(entry["min"]), center, ty(entry["max"]), i,
               center - half, ty(q3), half * 2,
               max(ty(q1) - ty(q3), 2), i, i,
               center - half, ty(med), center + half, ty(med), i))
        marks.append('<text x="%.1f" y="%d" text-anchor="middle">'
                     'exp %d</text>' % (center, height - pad + 14, i))
    return ('<div class="chart"><h2>Inter-token latency</h2>'
            '<p class="d">five-number summary per experiment '
            '(hover a box)</p>%s'
            '<svg viewBox="0 0 %d %d" width="100%%">%s%s</svg></div>'
            % (_legend(len(stats_list)), width, height, grid,
               "".join(marks)))


def _heatmap(stats_list) -> str:
    sequences = []
    for stats in stats_list:
        sequences.extend(
            [g / 1e6 for g in seq]
            for seq in getattr(stats.metrics, "itl_sequences_ns", []))
    sequences = [s for s in sequences if s]
    if not sequences:
        return ""
    sequences = sequences[:48]  # keep the SVG bounded
    width, pad = 920, 58
    cols = max(len(s) for s in sequences)
    cell_w = min((width - pad - 28) / cols, 34)
    cell_h = min(max(180 // len(sequences), 6), 22)
    height = len(sequences) * cell_h + 70
    v_hi = max(max(s) for s in sequences) or 1.0
    cells = []
    for row, seq in enumerate(sequences):
        for col, v in enumerate(seq):
            color = SEQ_RAMP[min(int(v / v_hi * (len(SEQ_RAMP) - 1) + 0.5),
                                 len(SEQ_RAMP) - 1)]
            cells.append(
                '<rect x="%.1f" y="%d" width="%.1f" height="%d" '
                'fill="%s" data-tip="request %d · token %d · %s ms"/>'
                % (pad + 8 + col * cell_w, 8 + row * cell_h,
                   max(cell_w - 1, 1), cell_h - 1, color, row, col + 1,
                   _fmt(v)))
    legend = "".join(
        '<rect x="%d" y="%d" width="16" height="10" fill="%s"/>'
        % (pad + 8 + i * 16, len(sequences) * cell_h + 24, c)
        for i, c in enumerate(SEQ_RAMP))
    scale_text = ('<text x="%d" y="%d">0 ms</text>'
                  '<text x="%d" y="%d">%s ms</text>'
                  % (pad + 8, len(sequences) * cell_h + 48,
                     pad + 8 + len(SEQ_RAMP) * 16 + 6,
                     len(sequences) * cell_h + 34, _fmt(v_hi)))
    return ('<div class="chart"><h2>Inter-token latency by token '
            'position</h2><p class="d">rows are requests; vertical bands '
            'are delivery stalls</p>'
            '<svg viewBox="0 0 %d %d" width="100%%">%s%s%s'
            '<text x="%d" y="%d" text-anchor="middle">token position'
            '</text></svg></div>'
            % (width, height, "".join(cells), legend, scale_text,
               (width + pad) // 2, len(sequences) * cell_h + 64))


def _tiles(stats_list) -> str:
    s0 = stats_list[0]
    ttft = s0.stats.get("time_to_first_token_ms", {})
    itl = s0.stats.get("inter_token_latency_ms", {})
    tiles = [
        (_fmt(s0.metrics.request_throughput_per_s), "requests / s"),
        (_fmt(s0.metrics.output_token_throughput_per_s), "tokens / s"),
        (_fmt(ttft.get("p50", 0.0)), "TTFT p50 (ms)"),
        (_fmt(ttft.get("p99", 0.0)), "TTFT p99 (ms)"),
        (_fmt(itl.get("p50", 0.0)), "ITL p50 (ms)"),
        (_fmt(itl.get("p99", 0.0)), "ITL p99 (ms)"),
    ]
    # Server-observed twins (scraped /metrics histograms) when a
    # metrics URL was supplied: client-vs-server TTFT side by side IS
    # the network/queueing decomposition.
    server_ttft = s0.stats.get("server_time_to_first_token_ms")
    server_itl = s0.stats.get("server_inter_token_latency_ms")
    if server_ttft:
        tiles.append((_fmt(server_ttft.get("p99", 0.0)),
                      "server TTFT p99 (ms)"))
    if server_itl:
        tiles.append((_fmt(server_itl.get("p99", 0.0)),
                      "server ITL p99 (ms)"))
    return '<div class="tiles">%s</div>' % "".join(
        '<div class="tile"><div class="v">%s</div><div class="l">%s</div>'
        '</div>' % (v, l) for v, l in tiles)


def _table(stats_list) -> str:
    metrics = ["time_to_first_token_ms", "server_time_to_first_token_ms",
               "inter_token_latency_ms", "server_inter_token_latency_ms",
               "request_latency_ms", "server_request_latency_ms",
               "output_token_count"]
    cols = ["mean", "p50", "p90", "p99"]
    rows = []
    for i, stats in enumerate(stats_list):
        for metric in metrics:
            entry = stats.stats.get(metric)
            if not entry:
                continue
            rows.append("<tr><td>exp %d · %s</td>%s</tr>" % (
                i, metric,
                "".join("<td>%s</td>"
                        % (_fmt(entry[c]) if c in entry else "–")
                        for c in cols)))
    return ('<details open><summary>Summary table (all experiments)'
            '</summary><table class="stats"><tr><th>metric</th>%s</tr>%s'
            '</table></details>'
            % ("".join("<th>%s</th>" % c for c in cols), "".join(rows)))


def generate_html_report(stats_list: List[Statistics], artifact_dir: str,
                         title: str = "") -> str:
    """Write `report.html`; returns the path."""
    os.makedirs(artifact_dir, exist_ok=True)
    # data() rebuilds every ns->ms converted list per call — convert
    # once per experiment, share across charts.
    data_list = [s.metrics.data() for s in stats_list]
    body = "".join([
        _tiles(stats_list),
        _scatter(data_list, len(stats_list)),
        _histogram(data_list, len(stats_list)),
        _boxes(stats_list),
        _heatmap(stats_list),
        _table(stats_list),
    ])
    doc = ("<!doctype html><html><head><meta charset=\"utf-8\">"
           "<title>%s</title><style>%s%s</style></head><body>"
           "<div class=\"viz-root\"><h1>%s</h1>"
           "<p class=\"sub\">%d experiment(s) · generated by "
           "tpu-genai-perf</p>%s</div><div id=\"tip\"></div>"
           "<script>%s</script></body></html>"
           % (html.escape(title or "LLM benchmark report"), _CSS,
              _series_vars(), html.escape(title or "LLM benchmark report"),
              len(stats_list), body, _JS))
    path = os.path.join(artifact_dir, "report.html")
    with open(path, "w") as f:
        f.write(doc)
    return path
