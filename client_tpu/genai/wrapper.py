"""Perf-harness invocation wrapper (parity: genai-perf wrapper.py,
which renders a perf_analyzer command line; here the harness is the
in-repo client_tpu.perf CLI, invoked in-process with the same argv it
would receive as a subprocess)."""

from __future__ import annotations

from typing import List, Optional


class Profiler:
    @staticmethod
    def build_args(
        model: str,
        url: str = "localhost:8001",
        service_kind: str = "triton",
        protocol: str = "grpc",
        concurrency: int = 1,
        input_path: str = "llm_inputs.json",
        export_path: str = "profile_export.json",
        measurement_interval_ms: int = 4000,
        stability_pct: float = 50.0,
        max_trials: int = 6,
        streaming: bool = True,
        measurement_mode: str = "time_windows",
        measurement_request_count: int = 50,
        extra_args: Optional[List[str]] = None,
    ) -> List[str]:
        args = [
            "-m", model,
            "--service-kind", service_kind,
            "--input-data", input_path,
            "--profile-export-file", export_path,
            "--concurrency-range", str(concurrency),
            "--measurement-interval", str(measurement_interval_ms),
            "--stability-percentage", str(stability_pct),
            "--max-trials", str(max_trials),
            "--measurement-mode", measurement_mode,
            "--measurement-request-count", str(measurement_request_count),
        ]
        if service_kind != "inprocess":
            args += ["-u", url, "-i", protocol]
        if streaming:
            args.append("--streaming")
        if extra_args:
            args += list(extra_args)
        return args

    @staticmethod
    def run(args: List[str], core=None) -> int:
        from client_tpu.perf.cli import run

        return run(args, core=core)
