"""Benchmark visualizations (parity: genai-perf plots/ — the
reference ships plotly scatter/box/heatmap; matplotlib is used here
since it is what the image provides).

All functions write PNG files into an artifact directory and return
the written paths.
"""

from __future__ import annotations

import os
from typing import List

from client_tpu.genai.metrics import Statistics


def _matplotlib():
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    return plt


def _boxplot(ax, series, names):
    # matplotlib 3.9 renamed boxplot's `labels` to `tick_labels`.
    try:
        ax.boxplot(series, tick_labels=names)
    except TypeError:
        ax.boxplot(series, labels=names)


def generate_plots(stats_list: List[Statistics], artifact_dir: str,
                   title: str = "") -> List[str]:
    """TTFT scatter, ITL box, request-latency distribution — one file
    each (parity: genai-perf ttft/itl/latency plot set)."""
    plt = _matplotlib()
    os.makedirs(artifact_dir, exist_ok=True)
    written: List[str] = []

    def save(fig, name: str):
        path = os.path.join(artifact_dir, name)
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        written.append(path)

    # 1. TTFT scatter per request, one series per experiment.
    fig, ax = plt.subplots(figsize=(7, 4))
    for idx, stats in enumerate(stats_list):
        samples = stats.metrics.data().get("time_to_first_token_ms", [])
        ax.scatter(range(len(samples)), samples, s=12,
                   label="experiment %d" % idx)
    ax.set_xlabel("request index")
    ax.set_ylabel("time to first token (ms)")
    ax.set_title(title or "Time to first token")
    if len(stats_list) > 1:
        ax.legend()
    save(fig, "time_to_first_token.png")

    # 2. Inter-token latency box plot per experiment.
    fig, ax = plt.subplots(figsize=(7, 4))
    series = [
        stats.metrics.data().get("inter_token_latency_ms", []) or [0.0]
        for stats in stats_list
    ]
    _boxplot(ax, series, ["exp %d" % i for i in range(len(series))])
    ax.set_ylabel("inter-token latency (ms)")
    ax.set_title(title or "Inter-token latency")
    save(fig, "inter_token_latency.png")

    # 3. Request latency histogram.
    fig, ax = plt.subplots(figsize=(7, 4))
    for idx, stats in enumerate(stats_list):
        samples = stats.metrics.data().get("request_latency_ms", [])
        if samples:
            ax.hist(samples, bins=min(30, max(5, len(samples) // 2)),
                    alpha=0.6, label="experiment %d" % idx)
    ax.set_xlabel("request latency (ms)")
    ax.set_ylabel("requests")
    ax.set_title(title or "Request latency distribution")
    if len(stats_list) > 1:
        ax.legend()
    save(fig, "request_latency.png")

    # 4. Token-position heatmap: requests (rows) x token position
    # (cols), colored by inter-token gap — makes chunked-delivery
    # stalls visible as vertical bands (parity: genai-perf's token
    # position vs latency heatmap).
    import numpy as np

    sequences = []
    for stats in stats_list:
        sequences.extend(
            [g / 1e6 for g in seq]
            for seq in getattr(stats.metrics, "itl_sequences_ns", [])
        )
    if sequences:
        width = max(len(seq) for seq in sequences)
        grid = np.full((len(sequences), width), np.nan)
        for row, seq in enumerate(sequences):
            grid[row, :len(seq)] = seq
        fig, ax = plt.subplots(figsize=(8, 4.5))
        image = ax.imshow(grid, aspect="auto", interpolation="nearest",
                          cmap="viridis")
        fig.colorbar(image, ax=ax, label="inter-token latency (ms)")
        ax.set_xlabel("token position")
        ax.set_ylabel("request")
        ax.set_title(title or "Inter-token latency by token position")
        save(fig, "token_position_heatmap.png")

    # 5. Per-experiment comparison: throughputs and latency summary
    # side by side (parity: genai-perf's cross-experiment comparison
    # plots for concurrency sweeps).
    fig, axes = plt.subplots(1, 3, figsize=(12, 4))
    labels = ["exp %d" % i for i in range(len(stats_list))]
    x = np.arange(len(stats_list))
    axes[0].bar(x, [s.metrics.request_throughput_per_s
                    for s in stats_list])
    axes[0].set_title("request throughput (/s)")
    axes[1].bar(x, [s.metrics.output_token_throughput_per_s
                    for s in stats_list])
    axes[1].set_title("token throughput (/s)")
    ttft_p50, ttft_p99 = [], []
    for stats in stats_list:
        entry = stats.stats.get("time_to_first_token_ms", {})
        ttft_p50.append(entry.get("p50", 0.0))
        ttft_p99.append(entry.get("p99", 0.0))
    bar_width = 0.4
    axes[2].bar(x - bar_width / 2, ttft_p50, bar_width, label="p50")
    axes[2].bar(x + bar_width / 2, ttft_p99, bar_width, label="p99")
    axes[2].set_title("TTFT (ms)")
    axes[2].legend()
    for ax in axes:
        ax.set_xticks(x)
        ax.set_xticklabels(labels)
    fig.suptitle(title or "Experiment comparison")
    save(fig, "experiment_comparison.png")

    return written
