"""Benchmark visualizations (parity: genai-perf plots/ — the
reference ships plotly scatter/box/heatmap; matplotlib is used here
since it is what the image provides).

All functions write PNG files into an artifact directory and return
the written paths.
"""

from __future__ import annotations

import os
from typing import List

from client_tpu.genai.metrics import Statistics


def _matplotlib():
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    return plt


def generate_plots(stats_list: List[Statistics], artifact_dir: str,
                   title: str = "") -> List[str]:
    """TTFT scatter, ITL box, request-latency distribution — one file
    each (parity: genai-perf ttft/itl/latency plot set)."""
    plt = _matplotlib()
    os.makedirs(artifact_dir, exist_ok=True)
    written: List[str] = []

    def save(fig, name: str):
        path = os.path.join(artifact_dir, name)
        fig.savefig(path, dpi=110, bbox_inches="tight")
        plt.close(fig)
        written.append(path)

    # 1. TTFT scatter per request, one series per experiment.
    fig, ax = plt.subplots(figsize=(7, 4))
    for idx, stats in enumerate(stats_list):
        samples = stats.metrics.data().get("time_to_first_token_ms", [])
        ax.scatter(range(len(samples)), samples, s=12,
                   label="experiment %d" % idx)
    ax.set_xlabel("request index")
    ax.set_ylabel("time to first token (ms)")
    ax.set_title(title or "Time to first token")
    if len(stats_list) > 1:
        ax.legend()
    save(fig, "time_to_first_token.png")

    # 2. Inter-token latency box plot per experiment.
    fig, ax = plt.subplots(figsize=(7, 4))
    series = [
        stats.metrics.data().get("inter_token_latency_ms", []) or [0.0]
        for stats in stats_list
    ]
    ax.boxplot(series,
               labels=["exp %d" % i for i in range(len(series))])
    ax.set_ylabel("inter-token latency (ms)")
    ax.set_title(title or "Inter-token latency")
    save(fig, "inter_token_latency.png")

    # 3. Request latency histogram.
    fig, ax = plt.subplots(figsize=(7, 4))
    for idx, stats in enumerate(stats_list):
        samples = stats.metrics.data().get("request_latency_ms", [])
        if samples:
            ax.hist(samples, bins=min(30, max(5, len(samples) // 2)),
                    alpha=0.6, label="experiment %d" % idx)
    ax.set_xlabel("request latency (ms)")
    ax.set_ylabel("requests")
    ax.set_title(title or "Request latency distribution")
    if len(stats_list) > 1:
        ax.legend()
    save(fig, "request_latency.png")

    return written
