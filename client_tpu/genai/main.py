"""genai CLI: benchmark an LLM generate endpoint end to end.

Run:  python -m client_tpu.genai -m llm --service-kind inprocess \
          --num-prompts 8 --output-tokens-mean 16

Pipeline parity with genai-perf main.py:46-120 — generate inputs,
run the perf harness, parse the profile export, report LLM metrics.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
from typing import List, Optional

from client_tpu.genai.exporters import (
    console_report,
    export_csv,
    export_json,
)
from client_tpu.genai.inputs import LlmInputs, OutputFormat
from client_tpu.genai.metrics import LLMProfileDataParser
from client_tpu.genai.tokenizer import get_tokenizer
from client_tpu.genai.wrapper import Profiler


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="client_tpu.genai",
        description="LLM benchmark front-end (TTFT / inter-token "
                    "latency / token throughput)")
    parser.add_argument("-m", "--model", required=True)
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("--endpoint", default="v1/chat/completions",
                        help="openai service-kind request path")
    parser.add_argument("--service-kind", default="triton",
                        choices=["triton", "inprocess", "openai"])
    parser.add_argument("-i", "--protocol", default="grpc",
                        choices=["grpc", "http"])
    parser.add_argument("--concurrency", type=int, default=1)
    parser.add_argument("--num-prompts", type=int, default=8)
    parser.add_argument("--synthetic-input-tokens-mean", type=int,
                        default=64)
    parser.add_argument("--synthetic-input-tokens-stddev", type=float,
                        default=0.0)
    parser.add_argument("--output-tokens-mean", type=int, default=16)
    parser.add_argument("--tokenizer", default="byte")
    parser.add_argument("--input-file", default=None,
                        help="prompts: JSONL with text_input, or raw lines")
    parser.add_argument("--input-dataset", default=None,
                        choices=["openorca", "cnn_dailymail"],
                        help="public dataset prompts (network-gated; "
                             "falls back to synthetic offline)")
    parser.add_argument("--measurement-interval", type=int, default=4000)
    parser.add_argument("--stability-percentage", type=float, default=50.0)
    parser.add_argument("--max-trials", type=int, default=6)
    parser.add_argument("--artifact-dir", default=None,
                        help="keep inputs/exports here (default: temp)")
    parser.add_argument("--profile-export-file", default=None)
    parser.add_argument("--export-json", default=None)
    parser.add_argument("--export-csv", default=None)
    parser.add_argument("--export-parquet", default=None)
    parser.add_argument("--generate-plots", action="store_true",
                        help="write TTFT/ITL/latency PNGs to the "
                             "artifact dir")
    parser.add_argument("--random-seed", type=int, default=0)
    parser.add_argument("--no-streaming", action="store_true")
    parser.add_argument("--measurement-mode", default="time_windows",
                        choices=["time_windows", "count_windows"],
                        help="count_windows holds each window open "
                             "until --measurement-request-count "
                             "requests complete (robust on slow or "
                             "contended backends)")
    parser.add_argument("--measurement-request-count", type=int,
                        default=50)
    parser.add_argument("--server-metrics-url", default=None,
                        help="Prometheus /metrics URL of the serving "
                             "endpoint; when given, the report joins "
                             "the server-observed TTFT/ITL histograms "
                             "(scraped before/after the run) beside "
                             "the client-observed numbers")
    return parser


def run(argv: Optional[List[str]] = None, core=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        tokenizer = get_tokenizer(args.tokenizer)
    except ValueError as e:
        print("genai failed: %s" % e, file=sys.stderr)
        return 1

    artifact_dir = args.artifact_dir or tempfile.mkdtemp(prefix="genai_")
    os.makedirs(artifact_dir, exist_ok=True)
    # Tell the user where inputs/profile export land (genai-perf
    # prints its artifact directory too); default runs use a temp dir.
    print("genai artifacts: %s" % artifact_dir, file=sys.stderr)
    input_path = os.path.join(artifact_dir, "llm_inputs.json")
    export_path = (args.profile_export_file
                   or os.path.join(artifact_dir, "profile_export.json"))

    inputs = LlmInputs(tokenizer, seed=args.random_seed)
    try:
        if args.input_dataset:
            from client_tpu.genai.datasets import dataset_prompts
            from client_tpu.genai.synthetic import SyntheticPromptGenerator

            prompts = dataset_prompts(
                args.input_dataset, args.num_prompts,
                fallback_generator=SyntheticPromptGenerator(
                    tokenizer, args.random_seed),
                fallback_tokens_mean=args.synthetic_input_tokens_mean,
                fallback_tokens_stddev=args.synthetic_input_tokens_stddev,
            )
        else:
            prompts = inputs.create_prompts(
                num_prompts=args.num_prompts,
                input_tokens_mean=args.synthetic_input_tokens_mean,
                input_tokens_stddev=args.synthetic_input_tokens_stddev,
                input_file=args.input_file,
            )
    except (OSError, ValueError) as e:
        print("genai failed: %s" % e, file=sys.stderr)
        return 1
    output_format = (
        OutputFormat.OPENAI_CHAT if args.service_kind == "openai"
        else OutputFormat.TRITON_GENERATE
    )
    dataset = inputs.convert_to_dataset(
        prompts, output_format,
        output_tokens_mean=args.output_tokens_mean,
        model_name=args.model,
    )
    inputs.write_dataset(dataset, input_path)

    perf_args = Profiler.build_args(
        model=args.model, url=args.url, service_kind=args.service_kind,
        protocol=args.protocol, concurrency=args.concurrency,
        input_path=input_path, export_path=export_path,
        measurement_interval_ms=args.measurement_interval,
        stability_pct=args.stability_percentage,
        max_trials=args.max_trials,
        streaming=not args.no_streaming,
        measurement_mode=args.measurement_mode,
        measurement_request_count=args.measurement_request_count,
        extra_args=(["--endpoint", args.endpoint]
                    if args.service_kind == "openai" else None),
    )
    metrics_before = None
    if args.server_metrics_url:
        from client_tpu.genai.metrics import fetch_metrics_text

        try:
            # Bracketing scrapes: cumulative-histogram deltas between
            # them isolate THIS run's server-observed distributions.
            metrics_before = fetch_metrics_text(args.server_metrics_url)
        except Exception as e:  # noqa: BLE001 — metrics are optional
            print("genai: server metrics unreachable at %s (%s); "
                  "continuing without server-side columns"
                  % (args.server_metrics_url, e), file=sys.stderr)
    rc = Profiler.run(perf_args, core=core)
    if rc != 0:
        return rc

    parser_obj = LLMProfileDataParser(export_path, tokenizer)
    stats_list = [parser_obj.get_statistics(i)
                  for i in range(len(parser_obj.experiments))]
    if metrics_before is not None:
        from client_tpu.genai.metrics import (
            fetch_metrics_text,
            parse_server_histograms,
        )

        try:
            metrics_after = fetch_metrics_text(args.server_metrics_url)
            server_rows = parse_server_histograms(
                metrics_before, metrics_after, args.model)
        except Exception as e:  # noqa: BLE001 — metrics are optional
            print("genai: post-run server metrics scrape failed (%s)"
                  % e, file=sys.stderr)
            server_rows = {}
        if server_rows and len(stats_list) == 1:
            stats_list[0].stats.update(server_rows)
        elif server_rows:
            # The bracketing scrapes cover the WHOLE run; stamping the
            # same aggregate into every experiment would misrepresent
            # it as per-experiment. Report it once, clearly run-wide.
            print("\nServer-side histograms (whole run, all "
                  "experiments):")
            for name, entry in sorted(server_rows.items()):
                print("    %-32s mean %8.2f  p50 %8.2f  p99 %8.2f"
                      % (name, entry["mean"], entry["p50"],
                         entry["p99"]))
        else:
            print("genai: no server-side stream histograms for model "
                  "'%s' in the scrape window" % args.model,
                  file=sys.stderr)
    for stats in stats_list:
        print(console_report(stats))
    if args.export_json:
        export_json(stats_list, args.export_json,
                    meta={"model": args.model,
                          "concurrency": args.concurrency,
                          "num_prompts": len(prompts)})
    if args.export_csv:
        export_csv(stats_list, args.export_csv)
    if args.export_parquet:
        from client_tpu.genai.exporters import export_parquet

        export_parquet(stats_list, args.export_parquet)
    if args.generate_plots:
        from client_tpu.genai.html_report import generate_html_report
        from client_tpu.genai.plots import generate_plots

        for path in generate_plots(stats_list, artifact_dir,
                                   title=args.model):
            print("genai plot: %s" % path, file=sys.stderr)
        print("genai plot: %s"
              % generate_html_report(stats_list, artifact_dir,
                                     title=args.model), file=sys.stderr)
    return 0


def main():
    sys.exit(run())


if __name__ == "__main__":
    main()
