"""Transport-neutral core data model.

The reference duplicates InferInput/InferRequestedOutput/InferResult per
transport (grpc/_infer_input.py, http/_infer_input.py, ...); here a
single implementation carries tensor data and shared-memory references,
and each transport layer serializes it to its own wire form. Parity
surface: /root/reference/src/c++/library/common.h:237-563 and the Python
mirrors.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    np_to_wire_dtype,
    num_elements,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    tensor_byte_size,
)


class InferInput:
    """One named input tensor of an inference request.

    Data can be attached either from a numpy array
    (:meth:`set_data_from_numpy`) or as a reference into a registered
    shared-memory region (:meth:`set_shared_memory`) — system (POSIX) or
    TPU (HBM arena slice).
    """

    def __init__(self, name: str, shape: Sequence[int], datatype: str):
        self._name = name
        self._shape = [int(s) for s in shape]
        self._datatype = datatype
        self._parameters: dict = {}
        self._raw_data: Optional[bytes] = None
        self._np_data: Optional[np.ndarray] = None
        self._shm: Optional[Tuple[str, int, int]] = None  # (region, byte_size, offset)
        self._binary_data = True

    def name(self) -> str:
        return self._name

    def datatype(self) -> str:
        return self._datatype

    def shape(self) -> list:
        return self._shape

    def set_shape(self, shape: Sequence[int]) -> "InferInput":
        self._shape = [int(s) for s in shape]
        return self

    def parameters(self) -> dict:
        return self._parameters

    def set_parameter(self, key: str, value) -> "InferInput":
        self._parameters[key] = value
        return self

    def set_data_from_numpy(self, input_tensor: np.ndarray,
                            binary_data: bool = True) -> "InferInput":
        """Attach tensor data, validating dtype and shape against the
        declaration. BYTES tensors are length-prefix serialized; BF16
        accepts ml_dtypes.bfloat16 (or float) arrays.

        ``binary_data=False`` asks the HTTP transport to send this
        tensor as a JSON ``data`` array instead of the binary
        extension (parity: the reference HTTP client's kwarg) —
        interoperable with KServe servers that lack the binary
        protocol. Ignored by gRPC (protobuf raw contents are already
        binary)."""
        if not isinstance(input_tensor, np.ndarray):
            raise InferenceServerException("input tensor must be a numpy array")
        dtype = np_to_wire_dtype(input_tensor.dtype)
        if self._datatype != dtype and not (
            self._datatype == "BF16" and input_tensor.dtype.kind == "f"
        ):
            raise InferenceServerException(
                "got unexpected datatype %s from numpy array, expected %s"
                % (dtype, self._datatype)
            )
        valid_shape = input_tensor.ndim == len(self._shape) and all(
            int(a) == int(b) for a, b in zip(input_tensor.shape, self._shape)
        )
        if not valid_shape:
            raise InferenceServerException(
                "got unexpected numpy array shape %s, expected %s"
                % (list(input_tensor.shape), self._shape)
            )
        self._shm = None
        self._np_data = input_tensor
        if self._datatype == "BYTES":
            self._raw_data = serialize_byte_tensor(input_tensor).tobytes()
        elif self._datatype == "BF16":
            self._raw_data = serialize_bf16_tensor(input_tensor).tobytes()
        else:
            self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
        self._binary_data = bool(binary_data)
        return self

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferInput":
        """Reference a slice of a registered shared-memory region
        instead of inlining data on the wire (zero-copy path)."""
        self._raw_data = None
        self._np_data = None
        self._shm = (region_name, int(byte_size), int(offset))
        return self

    # -- accessors used by the transport layers --------------------------

    def raw_data(self) -> Optional[bytes]:
        return self._raw_data

    def binary_data(self) -> bool:
        return self._binary_data

    def numpy_data(self) -> Optional[np.ndarray]:
        return self._np_data

    def shared_memory(self) -> Optional[Tuple[str, int, int]]:
        return self._shm

    def validate(self) -> None:
        if self._raw_data is None and self._shm is None:
            raise InferenceServerException(
                "input '%s' has no data; call set_data_from_numpy or "
                "set_shared_memory" % self._name
            )
        if self._raw_data is not None and self._datatype not in ("BYTES",):
            expected = tensor_byte_size(self._datatype, self._shape)
            if expected >= 0 and len(self._raw_data) != expected:
                raise InferenceServerException(
                    "input '%s' got %d data bytes, expected %d for %s%s"
                    % (
                        self._name,
                        len(self._raw_data),
                        expected,
                        self._datatype,
                        self._shape,
                    )
                )


class InferRequestedOutput:
    """One requested output: optionally top-K classification results,
    binary-data preference (HTTP), or a shared-memory placement."""

    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._binary_data = binary_data
        self._class_count = int(class_count)
        self._parameters: dict = {}
        self._shm: Optional[Tuple[str, int, int]] = None

    def name(self) -> str:
        return self._name

    def binary_data(self) -> bool:
        return self._binary_data

    def class_count(self) -> int:
        return self._class_count

    def parameters(self) -> dict:
        return self._parameters

    def set_shared_memory(
        self, region_name: str, byte_size: int, offset: int = 0
    ) -> "InferRequestedOutput":
        self._shm = (region_name, int(byte_size), int(offset))
        return self

    def unset_shared_memory(self) -> "InferRequestedOutput":
        self._shm = None
        return self

    def shared_memory(self) -> Optional[Tuple[str, int, int]]:
        return self._shm


def build_request_parameters(
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[dict] = None,
) -> dict:
    """Normalize per-request options into the v2 ``parameters`` map the
    transports serialize (sequence_* only included when a sequence is in
    play, matching reference wire behavior)."""
    params = dict(parameters) if parameters else {}
    reserved = ("sequence_id", "sequence_start", "sequence_end", "priority", "timeout")
    for k in reserved:
        if k in params:
            raise InferenceServerException(
                "parameter '%s' is reserved; use the dedicated argument" % k
            )
    if sequence_id:
        params["sequence_id"] = int(sequence_id)
        params["sequence_start"] = bool(sequence_start)
        params["sequence_end"] = bool(sequence_end)
    if priority:
        params["priority"] = int(priority)
    if timeout is not None:
        params["timeout"] = int(timeout)
    return params
