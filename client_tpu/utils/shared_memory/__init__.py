"""System (POSIX) shared-memory utilities.

API-parity surface with the reference
``tritonclient.utils.shared_memory`` (utils/shared_memory/__init__.py:
93-260), which backs it with a small C extension; here ctypes
``shm_open``/``shm_unlink`` + stdlib ``mmap`` give the same zero-copy
behavior with no build step (the C++ ``shm_utils`` in ``native/``
serves the C++ stack).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import mmap
import os
from typing import List, Optional

import numpy as np

from client_tpu.utils import (
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


class SharedMemoryException(Exception):
    """Raised on any shared-memory operation failure."""


def _load_shm_lib():
    # shm_open lives in librt on older glibc, libc on newer.
    for name in ("rt", "c"):
        path = ctypes.util.find_library(name)
        if path is None:
            continue
        lib = ctypes.CDLL(path, use_errno=True)
        if hasattr(lib, "shm_open"):
            return lib
    raise SharedMemoryException("unable to locate shm_open in libc/librt")


_LIB = _load_shm_lib()
_LIB.shm_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint]
_LIB.shm_open.restype = ctypes.c_int
_LIB.shm_unlink.argtypes = [ctypes.c_char_p]
_LIB.shm_unlink.restype = ctypes.c_int

_O_RDWR = os.O_RDWR
_O_CREAT = os.O_CREAT


class SharedMemoryRegion:
    """Handle to a mapped POSIX shared-memory region."""

    def __init__(self, triton_shm_name: str, shm_key: str):
        self._triton_shm_name = triton_shm_name
        self._shm_key = shm_key
        self._byte_size = 0
        self._fd = -1
        self._mpg: Optional[mmap.mmap] = None
        self._created = False

    @property
    def name(self) -> str:
        return self._triton_shm_name

    @property
    def key(self) -> str:
        return self._shm_key

    @property
    def byte_size(self) -> int:
        return self._byte_size

    def buf(self) -> mmap.mmap:
        if self._mpg is None:
            raise SharedMemoryException("region is not mapped")
        return self._mpg


_mapped_regions: dict = {}


def create_shared_memory_region(
    triton_shm_name: str, shm_key: str, byte_size: int, create_only: bool = False
) -> SharedMemoryRegion:
    """Create (or attach, unless ``create_only``) and map the POSIX
    region ``shm_key`` of ``byte_size`` bytes."""
    region = SharedMemoryRegion(triton_shm_name, shm_key)
    flags = _O_RDWR | _O_CREAT
    if create_only:
        flags |= os.O_EXCL
    fd = _LIB.shm_open(shm_key.encode(), flags, 0o600)
    if fd < 0:
        err = ctypes.get_errno()
        raise SharedMemoryException(
            "unable to create shared memory region '%s': %s"
            % (shm_key, os.strerror(err))
        )
    try:
        stat = os.fstat(fd)
        region._created = stat.st_size == 0
        if stat.st_size < byte_size:
            os.ftruncate(fd, byte_size)
        region._fd = fd
        region._byte_size = byte_size
        region._mpg = mmap.mmap(fd, byte_size)
    except OSError as e:
        os.close(fd)
        raise SharedMemoryException(
            "unable to map shared memory region '%s': %s" % (shm_key, e)
        )
    _mapped_regions[triton_shm_name] = region
    return region


def attach_shared_memory_region(
    triton_shm_name: str, shm_key: str, byte_size: int
) -> SharedMemoryRegion:
    """Attach to an existing region without creating it (used
    server-side when a client registers a region)."""
    region = SharedMemoryRegion(triton_shm_name, shm_key)
    fd = _LIB.shm_open(shm_key.encode(), _O_RDWR, 0o600)
    if fd < 0:
        raise SharedMemoryException(
            "unable to open shared memory region '%s': %s"
            % (shm_key, os.strerror(ctypes.get_errno()))
        )
    try:
        size = os.fstat(fd).st_size
        if size < byte_size:
            raise SharedMemoryException(
                "region '%s' is %d bytes, %d requested"
                % (shm_key, size, byte_size)
            )
        region._fd = fd
        region._byte_size = byte_size
        region._mpg = mmap.mmap(fd, byte_size)
    except SharedMemoryException:
        os.close(fd)
        raise
    except OSError as e:
        os.close(fd)
        raise SharedMemoryException(str(e))
    return region


def set_shared_memory_region(
    shm_handle: SharedMemoryRegion, input_values, offset: int = 0
) -> None:
    """Copy a list of numpy arrays into the region back to back
    starting at ``offset`` (BYTES arrays are wire-serialized)."""
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException("input_values must be a list of numpy arrays")
    buf = shm_handle.buf()
    pos = offset
    for arr in input_values:
        if arr.dtype.kind in ("O", "S", "U"):
            data = serialize_byte_tensor(arr).tobytes()
        else:
            data = np.ascontiguousarray(arr).tobytes()
        if pos + len(data) > shm_handle.byte_size:
            raise SharedMemoryException("input exceeds shared memory region size")
        buf[pos : pos + len(data)] = data
        pos += len(data)


def get_contents_as_numpy(
    shm_handle: SharedMemoryRegion, datatype, shape, offset: int = 0
) -> np.ndarray:
    """View/copy the region contents as a numpy array of
    datatype/shape. Fixed-size dtypes return a zero-copy view."""
    buf = shm_handle.buf()
    if isinstance(datatype, str):
        np_dtype = triton_to_np_dtype(datatype)
        wire = datatype
    else:
        np_dtype = np.dtype(datatype)
        wire = None
    if np_dtype == np.object_ or wire == "BYTES":
        end = shm_handle.byte_size
        return deserialize_bytes_tensor(bytes(buf[offset:end])).reshape(shape)
    count = int(np.prod(shape)) if len(shape) else 1
    return np.frombuffer(
        memoryview(buf), dtype=np_dtype, count=count, offset=offset
    ).reshape(shape)


def get_shared_memory_handle_info(shm_handle: SharedMemoryRegion):
    """(shm_key, byte_size, fd) of the underlying region."""
    return (shm_handle.key, shm_handle.byte_size, shm_handle._fd)


def mapped_shared_memory_regions() -> List[str]:
    return list(_mapped_regions.keys())


def _release_mapping(shm_handle: SharedMemoryRegion) -> None:
    # Zero-copy numpy views may still reference the mapping; in that
    # case dropping our reference lets GC unmap once the views die.
    if shm_handle._mpg is not None:
        try:
            shm_handle._mpg.close()
        except BufferError:
            pass
        shm_handle._mpg = None
    if shm_handle._fd >= 0:
        os.close(shm_handle._fd)
        shm_handle._fd = -1


def destroy_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap and unlink the region."""
    try:
        _release_mapping(shm_handle)
    finally:
        _mapped_regions.pop(shm_handle.name, None)
        _LIB.shm_unlink(shm_handle.key.encode())


def detach_shared_memory_region(shm_handle: SharedMemoryRegion) -> None:
    """Unmap without unlinking (server detaching a client's region)."""
    _release_mapping(shm_handle)
