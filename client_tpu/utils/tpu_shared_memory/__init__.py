"""TPU shared memory — zero-copy device tensor I/O.

The re-target of the reference's ``tritonclient.utils.cuda_shared_memory``
(utils/cuda_shared_memory/__init__.py:107-414) at TPU HBM. Same
seven-function surface:

    create_shared_memory_region(name, byte_size, device_id)
    get_raw_handle(handle)
    set_shared_memory_region(handle, values)
    set_shared_memory_region_from_dlpack(handle, tensor)
    get_contents_as_numpy(handle, datatype, shape)
    as_shared_memory_tensor(handle, datatype, shape)
    destroy_shared_memory_region(handle)

TPU difference: CUDA lets any process cudaMalloc and export an IPC
handle; on TPU a single process owns the device, so regions are slots
in the *server's* HBM arena and this module talks to the arena
service (same port as inference) — or directly to an in-process
``TpuArena``. The handle is a logical descriptor, not a pointer; pass
it to ``register_tpu_shared_memory`` exactly like the CUDA raw
handle. Region population is one host->device hop; the inference
request path is zero-copy (the server hands slot arrays straight to
the jitted model and stores outputs by reference swap).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    np_to_wire_dtype,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


class TpuSharedMemoryException(InferenceServerException):
    pass


class _ArenaTransport:
    """Uniform view over an in-process TpuArena or a remote arena
    service stub."""

    def __init__(self, arena=None, stub=None, channel=None):
        self.arena = arena
        self.stub = stub
        self.channel = channel

    @staticmethod
    def _rpc(call, request):
        import grpc

        try:
            return call(request)
        except grpc.RpcError as rpc_error:
            try:
                code, details = rpc_error.code().name, rpc_error.details()
            except Exception:
                code, details = None, str(rpc_error)
            raise TpuSharedMemoryException(details, status=code) from None

    def create(self, byte_size: int, device_id: int):
        if self.arena is not None:
            raw = self.arena.create_region(byte_size, device_id)
            import json

            return raw, json.loads(raw)["region_id"]
        from client_tpu.protocol import arena_pb2

        response = self._rpc(
            self.stub.CreateRegion,
            arena_pb2.CreateRegionRequest(
                byte_size=byte_size, device_id=device_id
            ),
        )
        return response.raw_handle, response.region_id

    def write(self, region_id, offset, data, datatype="", shape=None):
        if self.arena is not None:
            self.arena.write(region_id, offset, data, datatype, shape)
            return
        from client_tpu.protocol import arena_pb2

        self._rpc(
            self.stub.WriteRegion,
            arena_pb2.WriteRegionRequest(
                region_id=region_id, offset=offset, data=data,
                datatype=datatype or "", shape=shape or [],
            ),
        )

    def read(self, region_id, offset, byte_size) -> bytes:
        if self.arena is not None:
            return self.arena.read(region_id, offset, byte_size)
        from client_tpu.protocol import arena_pb2

        return self._rpc(
            self.stub.ReadRegion,
            arena_pb2.ReadRegionRequest(
                region_id=region_id, offset=offset, byte_size=byte_size
            ),
        ).data

    def destroy(self, region_id):
        if self.arena is not None:
            self.arena.destroy_region(region_id)
            return
        from client_tpu.protocol import arena_pb2

        self._rpc(
            self.stub.DestroyRegion,
            arena_pb2.DestroyRegionRequest(region_id=region_id),
        )


_default_transport: Optional[_ArenaTransport] = None
_transport_lock = threading.Lock()
allocated_shm_regions: Dict[str, "TpuSharedMemoryHandle"] = {}


def reset_arena_endpoint() -> None:
    """Clears the module transport, closing any gRPC channel it owns
    (the teardown twin of set_arena_endpoint / set_arena)."""
    _swap_transport(None)


def _swap_transport(new) -> None:
    global _default_transport
    with _transport_lock:
        old, _default_transport = _default_transport, new
    if old is not None and getattr(old, "channel", None) is not None:
        old.channel.close()


def set_arena(arena) -> None:
    """Use an in-process TpuArena (co-located / C-API-analogue mode —
    the cleanest zero-copy story, SURVEY.md §5 'distributed
    communication backend')."""
    _swap_transport(_ArenaTransport(arena=arena))


def set_arena_endpoint(url: str) -> None:
    """Point this module at a server's arena service (gRPC url, same
    port as the inference service)."""
    import grpc

    from client_tpu.server.arena_service import TpuArenaStub

    channel = grpc.insecure_channel(
        url,
        options=[
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
        ],
    )
    _swap_transport(_ArenaTransport(stub=TpuArenaStub(channel),
                                    channel=channel))


def _transport() -> _ArenaTransport:
    if _default_transport is None:
        raise TpuSharedMemoryException(
            "no TPU arena configured; call set_arena_endpoint(url) or "
            "set_arena(arena) first"
        )
    return _default_transport


class TpuSharedMemoryHandle:
    def __init__(self, name: str, byte_size: int, device_id: int,
                 raw_handle: bytes, region_id: str,
                 transport: _ArenaTransport):
        self._name = name
        self._byte_size = byte_size
        self._device_id = device_id
        self._raw_handle = raw_handle
        self._region_id = region_id
        self._transport = transport

    @property
    def name(self) -> str:
        return self._name

    @property
    def byte_size(self) -> int:
        return self._byte_size

    @property
    def device_id(self) -> int:
        return self._device_id


def create_shared_memory_region(
    triton_shm_name: str, byte_size: int, device_id: int = 0
) -> TpuSharedMemoryHandle:
    """Allocate an HBM region slot of byte_size bytes on device_id
    (parity: cuda create_shared_memory_region :107)."""
    transport = _transport()
    raw_handle, region_id = transport.create(byte_size, device_id)
    handle = TpuSharedMemoryHandle(
        triton_shm_name, byte_size, device_id, raw_handle, region_id,
        transport,
    )
    allocated_shm_regions[triton_shm_name] = handle
    return handle


def get_raw_handle(tpu_shm_handle: TpuSharedMemoryHandle) -> bytes:
    """The serialized region descriptor to pass to
    register_tpu_shared_memory (parity: cuda get_raw_handle :152,
    which base64s the cudaIpcMemHandle_t)."""
    return tpu_shm_handle._raw_handle


def set_shared_memory_region(
    tpu_shm_handle: TpuSharedMemoryHandle, input_values, offset: int = 0
) -> None:
    """Copy numpy arrays into the region (one host->device hop).
    A single array at offset 0 is stored typed, so inference consumes
    it with zero reinterpretation (parity: cuda
    set_shared_memory_region :173)."""
    if not isinstance(input_values, (list, tuple)):
        raise TpuSharedMemoryException(
            "input_values must be a list of numpy arrays"
        )
    transport = tpu_shm_handle._transport
    pos = offset
    for arr in input_values:
        datatype = np_to_wire_dtype(arr.dtype)
        if datatype == "BYTES":
            data = serialize_byte_tensor(arr).tobytes()
        else:
            data = np.ascontiguousarray(arr).tobytes()
        # dtype/shape ride with every tensor, so multi-tensor layouts
        # become typed device segments (no raw-byte degradation).
        transport.write(
            tpu_shm_handle._region_id, pos, data, datatype,
            list(arr.shape)
        )
        pos += len(data)


def set_shared_memory_region_from_dlpack(
    tpu_shm_handle: TpuSharedMemoryHandle, input_value
) -> None:
    """Ingest any DLPack-capable tensor (torch, jax, numpy...). An
    in-process jax.Array on the right device is stored by reference
    (true zero copy); anything else crosses host->device once
    (parity: cuda set_shared_memory_region_from_dlpack :328)."""
    transport = tpu_shm_handle._transport
    if transport.arena is not None and _is_jax_array(input_value):
        transport.arena.store(
            tpu_shm_handle._region_id, 0, tpu_shm_handle._byte_size,
            input_value,
        )
        return
    host = _dlpack_to_numpy(input_value)
    datatype = np_to_wire_dtype(host.dtype)
    transport.write(
        tpu_shm_handle._region_id, 0,
        np.ascontiguousarray(host).tobytes(), datatype, list(host.shape),
    )


def _is_jax_array(value) -> bool:
    try:
        import jax

        return isinstance(value, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def _dlpack_to_numpy(value) -> np.ndarray:
    if isinstance(value, np.ndarray):
        return value
    # Host tensors: zero-copy ctypes view via the standalone DLPack
    # layer (no framework import, parity: reference utils/_dlpack.py).
    from client_tpu.utils import _dlpack

    try:
        return _dlpack.to_numpy(value)
    except Exception:
        pass
    # device tensors: go through the producer's own host transfer
    if hasattr(value, "cpu"):  # torch
        return value.cpu().numpy()
    return np.asarray(value)


def get_contents_as_numpy(
    tpu_shm_handle: TpuSharedMemoryHandle, datatype, shape, offset: int = 0
) -> np.ndarray:
    """Region contents -> host numpy array (the inspection hop,
    parity: cuda get_contents_as_numpy :242)."""
    if isinstance(datatype, str):
        wire = datatype
    else:
        wire = np_to_wire_dtype(np.dtype(datatype))
    if wire == "BYTES":
        data = tpu_shm_handle._transport.read(
            tpu_shm_handle._region_id, offset, 0
        )
        return deserialize_bytes_tensor(data).reshape(shape)
    np_dtype = triton_to_np_dtype(wire) if wire else np.dtype(datatype)
    count = int(np.prod(shape)) if len(shape) else 1
    byte_size = count * np.dtype(np_dtype).itemsize
    data = tpu_shm_handle._transport.read(
        tpu_shm_handle._region_id, offset, byte_size
    )
    if wire == "BF16":
        return deserialize_bf16_tensor(data).reshape(shape)
    return np.frombuffer(data, dtype=np_dtype).reshape(shape)


class SharedMemoryTensor:
    """DLPack-capable view of a region (parity:
    utils/_shared_memory_tensor.py:34). In-process this wraps the live
    jax.Array (zero copy); remote it wraps a host snapshot."""

    def __init__(self, array):
        self._array = array

    def __dlpack__(self, stream=None):
        return self._array.__dlpack__()

    def __dlpack_device__(self):
        return self._array.__dlpack_device__()

    @property
    def array(self):
        return self._array


def as_shared_memory_tensor(
    tpu_shm_handle: TpuSharedMemoryHandle, datatype: str, shape
) -> SharedMemoryTensor:
    """Zero-copy device view of the region as datatype/shape (parity:
    cuda as_shared_memory_tensor :391)."""
    transport = tpu_shm_handle._transport
    if transport.arena is not None:
        return SharedMemoryTensor(
            transport.arena.as_typed_array(
                tpu_shm_handle._region_id, 0, tpu_shm_handle._byte_size,
                datatype, shape,
            )
        )
    return SharedMemoryTensor(
        get_contents_as_numpy(tpu_shm_handle, datatype, shape)
    )


def destroy_shared_memory_region(
    tpu_shm_handle: TpuSharedMemoryHandle,
) -> None:
    """Free the region slot (parity: cuda destroy_shared_memory_region
    :414)."""
    try:
        tpu_shm_handle._transport.destroy(tpu_shm_handle._region_id)
    finally:
        allocated_shm_regions.pop(tpu_shm_handle._name, None)


def allocated_shared_memory_regions() -> List[str]:
    return list(allocated_shm_regions.keys())
