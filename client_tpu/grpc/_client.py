"""Synchronous + callback-async gRPC client for the KServe-v2 protocol.

API-parity surface with the reference
tritonclient.grpc.InferenceServerClient (grpc/_client.py:119+), with
the CUDA shared-memory verbs re-targeted at TPU HBM regions.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence

import grpc
from google.protobuf import json_format

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu._plugin import InferenceServerClientBase
from client_tpu.grpc._utils import (
    InferResult,
    get_error_grpc,
    get_inference_request,
    raise_error,
    raise_error_grpc,
    set_parameter,
)
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub
from client_tpu.utils import InferenceServerException

# Default channel options: unlimited message sizes (tensors), matching
# the reference's MAX_GRPC_MESSAGE_SIZE unlimiting (grpc_client.cc).
_DEFAULT_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", -1),
    ("grpc.max_receive_message_length", -1),
]


class KeepAliveOptions:
    """GRPC keepalive knobs (reference grpc_client.h:62-82)."""

    def __init__(
        self,
        keepalive_time_ms: int = 2**31 - 1,
        keepalive_timeout_ms: int = 20000,
        keepalive_permit_without_calls: bool = False,
        http2_max_pings_without_data: int = 2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data

    def channel_args(self):
        return [
            ("grpc.keepalive_time_ms", self.keepalive_time_ms),
            ("grpc.keepalive_timeout_ms", self.keepalive_timeout_ms),
            (
                "grpc.keepalive_permit_without_calls",
                int(self.keepalive_permit_without_calls),
            ),
            (
                "grpc.http2.max_pings_without_data",
                self.http2_max_pings_without_data,
            ),
        ]


class CallContext:
    """Cancellation handle returned by :meth:`async_infer`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._call = None
        self._cancelled = False

    def _set_call(self, call):
        with self._lock:
            self._call = call
            if self._cancelled:
                call.cancel()

    def cancel(self):
        with self._lock:
            self._cancelled = True
            if self._call is not None:
                self._call.cancel()


def _metadata_from_headers(headers: Optional[dict]):
    if not headers:
        return None
    return tuple((str(k).lower(), str(v)) for k, v in headers.items())


class _InferStream:
    """Decoupled bidi stream: a queue-fed request iterator writes into
    ModelStreamInfer; a reader thread dispatches each response (or
    error) to the user callback. Mirrors the reference's
    _InferStream/_RequestIterator design (grpc/_infer_stream.py:38,170)."""

    _CLOSE = object()

    def __init__(self, callback: Callable, verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._request_queue: "queue.Queue" = queue.Queue()
        self._response_iterator = None
        self._worker: Optional[threading.Thread] = None
        self._active = True

    def _request_iterator(self):
        while True:
            item = self._request_queue.get()
            if item is self._CLOSE:
                return
            yield item

    def start(self, stub, metadata, timeout):
        self._response_iterator = stub.ModelStreamInfer(
            self._request_iterator(), metadata=metadata, timeout=timeout
        )
        self._worker = threading.Thread(target=self._process_responses, daemon=True)
        self._worker.start()

    def enqueue_request(self, request: pb.ModelInferRequest):
        if not self._active:
            raise_error("stream is closed")
        self._request_queue.put(request)

    def _process_responses(self):
        try:
            for response in self._response_iterator:
                if response.error_message:
                    self._callback(
                        None, InferenceServerException(response.error_message)
                    )
                else:
                    self._callback(InferResult(response.infer_response), None)
        except grpc.RpcError as rpc_error:
            if rpc_error.code() != grpc.StatusCode.CANCELLED:
                self._callback(None, get_error_grpc(rpc_error))
        except Exception as e:  # defensive: surface reader crashes
            self._callback(None, InferenceServerException(str(e)))

    def close(self, cancel_requests: bool = False):
        if not self._active:
            return
        self._active = False
        if cancel_requests and self._response_iterator is not None:
            self._response_iterator.cancel()
        self._request_queue.put(self._CLOSE)
        if self._worker is not None:
            self._worker.join()


class InferenceServerClient(InferenceServerClientBase):
    """A client talking to a KServe-v2 gRPC endpoint.

    One client owns one channel; ``infer`` is thread-safe, the
    stream-control methods are not (same contract as the reference,
    grpc_client.h:86-89).
    """

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[list] = None,
        retry_policy=None,
        circuit_breaker=None,
    ):
        super().__init__()
        self._url = url
        self._verbose = verbose
        # client_tpu.robust wiring: infer() retries retryable statuses
        # (UNAVAILABLE, ...) under the policy; the breaker fails fast
        # while open. Both default to off.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker
        options = list(_DEFAULT_CHANNEL_OPTIONS)
        if keepalive_options is not None:
            options += keepalive_options.channel_args()
        if channel_args is not None:
            options += list(channel_args)
        if creds is not None:
            self._channel = grpc.secure_channel(url, creds, options=options)
        elif ssl:
            rc = open(root_certificates, "rb").read() if root_certificates else None
            pk = open(private_key, "rb").read() if private_key else None
            cc = open(certificate_chain, "rb").read() if certificate_chain else None
            credentials = grpc.ssl_channel_credentials(rc, pk, cc)
            self._channel = grpc.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.insecure_channel(url, options=options)
        self._client_stub = GRPCInferenceServiceStub(self._channel)
        self._stream: Optional[_InferStream] = None

    # -- lifecycle -------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        self.stop_stream()
        self._channel.close()

    def _log(self, *args):
        if self._verbose:
            print(*args)

    def _metadata(self, headers):
        headers = self._call_plugin(dict(headers) if headers else {})
        return _metadata_from_headers(headers)

    # -- health / metadata ----------------------------------------------

    def is_server_live(self, headers=None, client_timeout=None) -> bool:
        try:
            response = self._client_stub.ServerLive(
                pb.ServerLiveRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.live
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        try:
            response = self._client_stub.ServerReady(
                pb.ServerReadyRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.ready
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ) -> bool:
        try:
            response = self._client_stub.ModelReady(
                pb.ModelReadyRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.ready
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        try:
            response = self._client_stub.ServerMetadata(
                pb.ServerMetadataRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_metadata(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        try:
            response = self._client_stub.ModelMetadata(
                pb.ModelMetadataRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_config(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        try:
            response = self._client_stub.ModelConfig(
                pb.ModelConfigRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_repository_index(self, headers=None, as_json=False,
                                   client_timeout=None):
        try:
            response = self._client_stub.RepositoryIndex(
                pb.RepositoryIndexRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- model control ---------------------------------------------------

    def load_model(
        self, model_name, headers=None, config=None, files=None, client_timeout=None
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files is not None:
            for path, content in files.items():
                request.parameters[path].bytes_param = content
        try:
            self._client_stub.RepositoryModelLoad(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            self._log("Loaded model '%s'" % model_name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        try:
            self._client_stub.RepositoryModelUnload(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            self._log("Unloaded model '%s'" % model_name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- statistics / settings ------------------------------------------

    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False,
        client_timeout=None
    ):
        try:
            response = self._client_stub.ModelStatistics(
                pb.ModelStatisticsRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def update_trace_settings(
        self, model_name="", settings=None, headers=None, as_json=False,
        client_timeout=None
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                request.settings[key]  # clears the setting
            elif isinstance(value, (list, tuple)):
                request.settings[key].value.extend(str(v) for v in value)
            else:
                request.settings[key].value.append(str(value))
        try:
            response = self._client_stub.TraceSetting(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_trace_settings(self, model_name="", headers=None, as_json=False,
                           client_timeout=None):
        """Pure read: the settings map is never touched, so no server
        implementation can mistake the request for a write."""
        try:
            response = self._client_stub.TraceSetting(
                pb.TraceSettingRequest(model_name=model_name or ""),
                metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def update_log_settings(self, settings, headers=None, as_json=False,
                            client_timeout=None):
        request = pb.LogSettingsRequest()
        for key, value in (settings or {}).items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        try:
            response = self._client_stub.LogSettings(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        """Pure read (see get_trace_settings)."""
        try:
            response = self._client_stub.LogSettings(
                pb.LogSettingsRequest(), metadata=self._metadata(headers),
                timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- shared memory ---------------------------------------------------

    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.SystemSharedMemoryStatus(
                pb.SystemSharedMemoryStatusRequest(name=region_name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        try:
            self._client_stub.SystemSharedMemoryRegister(
                pb.SystemSharedMemoryRegisterRequest(
                    name=name, key=key, offset=offset, byte_size=byte_size
                ),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            self._log("Registered system shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unregister_system_shared_memory(self, name="", headers=None,
                                        client_timeout=None):
        try:
            self._client_stub.SystemSharedMemoryUnregister(
                pb.SystemSharedMemoryUnregisterRequest(name=name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            self._log("Unregistered system shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.TpuSharedMemoryStatus(
                pb.TpuSharedMemoryStatusRequest(name=region_name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None,
        client_timeout=None
    ):
        """Register a TPU HBM region by its serialized handle (the TPU
        analogue of register_cuda_shared_memory, reference
        grpc/_client.py:1339)."""
        try:
            self._client_stub.TpuSharedMemoryRegister(
                pb.TpuSharedMemoryRegisterRequest(
                    name=name,
                    raw_handle=raw_handle,
                    device_id=device_id,
                    byte_size=byte_size,
                ),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            self._log("Registered TPU shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unregister_tpu_shared_memory(self, name="", headers=None,
                                     client_timeout=None):
        try:
            self._client_stub.TpuSharedMemoryUnregister(
                pb.TpuSharedMemoryUnregisterRequest(name=name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            self._log("Unregistered TPU shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # Drop-in aliases for code migrating from the CUDA client.
    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- inference -------------------------------------------------------

    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[dict] = None,
    ) -> InferResult:
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        metadata = self._metadata(headers)
        compression = _grpc_compression(compression_algorithm)

        def _attempt(remaining: Optional[float]) -> InferResult:
            # `remaining` is the shrinking share of client_timeout left
            # for this attempt (None = no deadline).
            try:
                response = self._client_stub.ModelInfer(
                    request,
                    metadata=metadata,
                    timeout=remaining,
                    compression=compression,
                )
                return InferResult(response)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        from client_tpu.robust import call_with_retry

        return call_with_retry(
            _attempt, self._retry_policy, self._breaker,
            deadline_s=client_timeout,
        )

    def async_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        callback: Callable,
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[dict] = None,
    ) -> CallContext:
        """Issue the request without blocking; ``callback(result,
        error)`` fires on the grpc completion thread. Returns a
        :class:`CallContext` whose ``cancel()`` aborts the call."""
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )

        def _done(call_future):
            try:
                result = InferResult(call_future.result())
                callback(result, None)
            except grpc.RpcError as rpc_error:
                callback(None, get_error_grpc(rpc_error))
            except grpc.FutureCancelledError:
                callback(None, InferenceServerException("request cancelled",
                                                        status="CANCELLED"))
            except Exception as e:
                callback(None, InferenceServerException(str(e)))

        context = CallContext()
        call_future = self._client_stub.ModelInfer.future(
            request,
            metadata=self._metadata(headers),
            timeout=client_timeout,
            compression=_grpc_compression(compression_algorithm),
        )
        context._set_call(call_future)
        call_future.add_done_callback(_done)
        return context

    # -- streaming -------------------------------------------------------

    def start_stream(
        self,
        callback: Callable,
        stream_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
    ):
        """Open the bidi ModelStreamInfer stream; every response (or
        error) is delivered to ``callback(result, error)``."""
        if self._stream is not None:
            raise_error("stream is already running; call stop_stream first")
        self._stream = _InferStream(callback, self._verbose)
        self._stream.start(self._client_stub, self._metadata(headers), stream_timeout)

    def stop_stream(self, cancel_requests: bool = False):
        if self._stream is not None:
            self._stream.close(cancel_requests)
            self._stream = None

    def async_stream_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        enable_empty_final_response: bool = False,
        parameters: Optional[dict] = None,
    ):
        if self._stream is None:
            raise_error("stream is not running; call start_stream first")
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        if enable_empty_final_response:
            request.parameters["triton_enable_empty_final_response"].bool_param = True
        self._stream.enqueue_request(request)


def _maybe_json(message, as_json: bool):
    if as_json:
        return json_format.MessageToDict(message, preserving_proto_field_name=True)
    return message


def _grpc_compression(algorithm: Optional[str]):
    if algorithm is None or algorithm == "none":
        return None
    if algorithm == "deflate":
        return grpc.Compression.Deflate
    if algorithm == "gzip":
        return grpc.Compression.Gzip
    raise_error("unsupported compression algorithm %s" % algorithm)
