"""Synchronous + callback-async gRPC client for the KServe-v2 protocol.

API-parity surface with the reference
tritonclient.grpc.InferenceServerClient (grpc/_client.py:119+), with
the CUDA shared-memory verbs re-targeted at TPU HBM regions.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional, Sequence

import grpc
from google.protobuf import json_format

from client_tpu import status_map
from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu._plugin import InferenceServerClientBase
from client_tpu.grpc._utils import (
    InferResult,
    get_error_grpc,
    get_inference_request,
    raise_error,
    raise_error_grpc,
    set_parameter,
)
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub
from client_tpu.utils import InferenceServerException

# Default channel options: unlimited message sizes (tensors), matching
# the reference's MAX_GRPC_MESSAGE_SIZE unlimiting (grpc_client.cc).
_DEFAULT_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", -1),
    ("grpc.max_receive_message_length", -1),
]


class KeepAliveOptions:
    """GRPC keepalive knobs (reference grpc_client.h:62-82)."""

    def __init__(
        self,
        keepalive_time_ms: int = 2**31 - 1,
        keepalive_timeout_ms: int = 20000,
        keepalive_permit_without_calls: bool = False,
        http2_max_pings_without_data: int = 2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data

    def channel_args(self):
        return [
            ("grpc.keepalive_time_ms", self.keepalive_time_ms),
            ("grpc.keepalive_timeout_ms", self.keepalive_timeout_ms),
            (
                "grpc.keepalive_permit_without_calls",
                int(self.keepalive_permit_without_calls),
            ),
            (
                "grpc.http2.max_pings_without_data",
                self.http2_max_pings_without_data,
            ),
        ]


class CallContext:
    """Cancellation handle returned by :meth:`async_infer`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._call = None
        self._cancelled = False

    def _set_call(self, call):
        with self._lock:
            self._call = call
            if self._cancelled:
                call.cancel()

    def cancel(self):
        with self._lock:
            self._cancelled = True
            if self._call is not None:
                self._call.cancel()


def _metadata_from_headers(headers: Optional[dict]):
    if not headers:
        return None
    return tuple((str(k).lower(), str(v)) for k, v in headers.items())


class _InferStream:
    """Decoupled bidi stream: a queue-fed request iterator writes into
    ModelStreamInfer; a reader thread dispatches each response (or
    error) to the user callback. Mirrors the reference's
    _InferStream/_RequestIterator design (grpc/_infer_stream.py:38,170)."""

    _CLOSE = object()

    def __init__(self, callback: Callable, verbose: bool = False):
        self._callback = callback
        self._verbose = verbose
        self._request_queue: "queue.Queue" = queue.Queue()
        self._response_iterator = None
        self._worker: Optional[threading.Thread] = None
        self._active = True

    def _request_iterator(self):
        while True:
            item = self._request_queue.get()
            if item is self._CLOSE:
                return
            yield item

    def start(self, stub, metadata, timeout):
        self._response_iterator = stub.ModelStreamInfer(
            self._request_iterator(), metadata=metadata, timeout=timeout
        )
        self._worker = threading.Thread(target=self._process_responses, daemon=True)
        self._worker.start()

    def enqueue_request(self, request: pb.ModelInferRequest):
        if not self._active:
            raise_error("stream is closed")
        self._request_queue.put(request)

    def _process_responses(self):
        try:
            for response in self._response_iterator:
                if response.error_message:
                    self._callback(
                        None, InferenceServerException(response.error_message)
                    )
                else:
                    self._callback(InferResult(response.infer_response), None)
        except grpc.RpcError as rpc_error:
            if status_map.status_of_grpc_code(
                    rpc_error.code()) != "CANCELLED":
                self._callback(None, get_error_grpc(rpc_error))
        except Exception as e:  # defensive: surface reader crashes
            self._callback(None, InferenceServerException(str(e)))

    def close(self, cancel_requests: bool = False):
        if not self._active:
            return
        self._active = False
        if cancel_requests and self._response_iterator is not None:
            self._response_iterator.cancel()
        self._request_queue.put(self._CLOSE)
        if self._worker is not None:
            self._worker.join()


def _channel_credentials(ssl, root_certificates, private_key,
                         certificate_chain, creds):
    """Resolve the credentials one channel needs (None = insecure)."""
    if creds is not None:
        return creds
    if not ssl:
        return None
    rc = open(root_certificates, "rb").read() if root_certificates else None
    pk = open(private_key, "rb").read() if private_key else None
    cc = open(certificate_chain, "rb").read() if certificate_chain else None
    return grpc.ssl_channel_credentials(rc, pk, cc)


def _make_channel(url, options, credentials, aio: bool = False):
    api = grpc.aio if aio else grpc
    if credentials is not None:
        return api.secure_channel(url, credentials, options=options)
    return api.insecure_channel(url, options=options)


def probe_grpc_ready(url, credentials, timeout: float) -> bool:
    """Bounded self-contained ServerReady probe: its own short-lived
    channel, independent of any client's transports — a shared
    EndpointPool's prober must keep working after the client that
    registered it closes (probes only run for ejected endpoints at the
    probe interval, so the per-probe channel cost is irrelevant)."""
    channel = None
    try:
        channel = _make_channel(url, list(_DEFAULT_CHANNEL_OPTIONS),
                                credentials)
        response = GRPCInferenceServiceStub(channel).ServerReady(
            pb.ServerReadyRequest(), timeout=timeout)
        return bool(response.ready)
    except Exception:  # noqa: BLE001 — any failure = not ready
        return False
    finally:
        if channel is not None:
            channel.close()


class InferenceServerClient(InferenceServerClientBase):
    """A client talking to one or more KServe-v2 gRPC endpoints.

    One client owns one channel per endpoint; ``infer`` is
    thread-safe, the stream-control methods are not (same contract as
    the reference, grpc_client.h:86-89).

    ``url`` may be a comma-separated endpoint list (or a list), or an
    :class:`client_tpu.robust.EndpointPool` may be passed as
    ``endpoint_pool``: ``infer`` then routes least-outstanding across
    healthy endpoints, fails over on retryable errors, hedges
    tail-slow requests within the pool's budget, and a background
    prober (ServerReady with a bounded timeout) readmits ejected
    endpoints. Streams stay pinned to the primary endpoint. With a
    pool, ``circuit_breaker`` is ignored — health is per endpoint,
    owned by the pool.

    ``tracer`` (:class:`client_tpu.tracing.ClientTracer`) records a
    client-side span per ``infer`` and propagates its W3C
    ``traceparent`` as gRPC metadata so the server's sampled span tree
    joins the client's trace; a caller-supplied ``traceparent`` in
    ``headers`` wins over the generated one.
    """

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[list] = None,
        retry_policy=None,
        circuit_breaker=None,
        endpoint_pool=None,
        tracer=None,
    ):
        super().__init__()
        from client_tpu.robust import EndpointPool

        urls = (endpoint_pool.urls if endpoint_pool is not None
                else EndpointPool.split_url(url))
        if not urls:
            raise InferenceServerException("invalid url '%s'" % url)
        self._url = urls[0]
        self._verbose = verbose
        self._owns_pool = endpoint_pool is None and len(urls) > 1
        self._endpoint_pool = (endpoint_pool if endpoint_pool is not None
                               else (EndpointPool(urls) if len(urls) > 1
                                     else None))
        # client_tpu.robust wiring: infer() retries retryable statuses
        # (UNAVAILABLE, ...) under the policy; the breaker fails fast
        # while open. Both default to off.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker if self._endpoint_pool is None \
            else None
        options = list(_DEFAULT_CHANNEL_OPTIONS)
        if keepalive_options is not None:
            options += keepalive_options.channel_args()
        if channel_args is not None:
            options += list(channel_args)
        credentials = _channel_credentials(
            ssl, root_certificates, private_key, certificate_chain, creds)
        self._channels = {
            u: _make_channel(u, options, credentials) for u in urls
        }
        self._stubs = {
            u: GRPCInferenceServiceStub(ch)
            for u, ch in self._channels.items()
        }
        self._channel = self._channels[urls[0]]
        self._client_stub = self._stubs[urls[0]]
        self._stream: Optional[_InferStream] = None
        self._tracer = tracer
        if self._endpoint_pool is not None:
            timeout = self._endpoint_pool.probe_timeout_s
            self._endpoint_pool.ensure_prober(
                lambda u, _creds=credentials: probe_grpc_ready(
                    u, _creds, timeout))

    # -- lifecycle -------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, traceback):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        if self._endpoint_pool is not None and self._owns_pool:
            self._endpoint_pool.close()
        self.stop_stream()
        for channel in self._channels.values():
            channel.close()

    def pool_stats(self) -> Optional[dict]:
        """EndpointPool snapshot (hedges/failovers/ejections + per-
        endpoint health); None for a single-endpoint client."""
        return (self._endpoint_pool.stats()
                if self._endpoint_pool is not None else None)

    def _log(self, *args):
        if self._verbose:
            print(*args)

    def _metadata(self, headers):
        headers = self._call_plugin(dict(headers) if headers else {})
        return _metadata_from_headers(headers)

    def _fleet_stubs(self):
        """Every endpoint's stub — control-plane verbs that mutate
        per-replica state (shm registration, model load/unload) must
        hit the whole fleet, not just the primary."""
        return list(self._stubs.values())

    # -- health / metadata ----------------------------------------------

    def is_server_live(self, headers=None, client_timeout=None) -> bool:
        try:
            response = self._client_stub.ServerLive(
                pb.ServerLiveRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.live
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        try:
            response = self._client_stub.ServerReady(
                pb.ServerReadyRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.ready
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ) -> bool:
        try:
            response = self._client_stub.ModelReady(
                pb.ModelReadyRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return response.ready
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        try:
            response = self._client_stub.ServerMetadata(
                pb.ServerMetadataRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_metadata(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        try:
            response = self._client_stub.ModelMetadata(
                pb.ModelMetadataRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_config(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        try:
            response = self._client_stub.ModelConfig(
                pb.ModelConfigRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_model_repository_index(self, headers=None, as_json=False,
                                   client_timeout=None):
        try:
            response = self._client_stub.RepositoryIndex(
                pb.RepositoryIndexRequest(),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- model control ---------------------------------------------------

    def load_model(
        self, model_name, headers=None, config=None, files=None, client_timeout=None
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        if files is not None:
            for path, content in files.items():
                request.parameters[path].bytes_param = content
        try:
            for stub in self._fleet_stubs():
                stub.RepositoryModelLoad(
                    request, metadata=self._metadata(headers),
                    timeout=client_timeout
                )
            self._log("Loaded model '%s'" % model_name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        try:
            for stub in self._fleet_stubs():
                stub.RepositoryModelUnload(
                    request, metadata=self._metadata(headers),
                    timeout=client_timeout
                )
            self._log("Unloaded model '%s'" % model_name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- statistics / settings ------------------------------------------

    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False,
        client_timeout=None
    ):
        try:
            response = self._client_stub.ModelStatistics(
                pb.ModelStatisticsRequest(name=model_name, version=model_version),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def update_trace_settings(
        self, model_name="", settings=None, headers=None, as_json=False,
        client_timeout=None
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                request.settings[key]  # clears the setting
            elif isinstance(value, (list, tuple)):
                request.settings[key].value.extend(str(v) for v in value)
            else:
                request.settings[key].value.append(str(value))
        try:
            response = self._client_stub.TraceSetting(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_trace_settings(self, model_name="", headers=None, as_json=False,
                           client_timeout=None):
        """Pure read: the settings map is never touched, so no server
        implementation can mistake the request for a write."""
        try:
            response = self._client_stub.TraceSetting(
                pb.TraceSettingRequest(model_name=model_name or ""),
                metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def update_log_settings(self, settings, headers=None, as_json=False,
                            client_timeout=None):
        request = pb.LogSettingsRequest()
        for key, value in (settings or {}).items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        try:
            response = self._client_stub.LogSettings(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        """Pure read (see get_trace_settings)."""
        try:
            response = self._client_stub.LogSettings(
                pb.LogSettingsRequest(), metadata=self._metadata(headers),
                timeout=client_timeout
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # -- shared memory ---------------------------------------------------

    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.SystemSharedMemoryStatus(
                pb.SystemSharedMemoryStatusRequest(name=region_name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        try:
            for stub in self._fleet_stubs():
                stub.SystemSharedMemoryRegister(
                    pb.SystemSharedMemoryRegisterRequest(
                        name=name, key=key, offset=offset, byte_size=byte_size
                    ),
                    metadata=self._metadata(headers),
                    timeout=client_timeout,
                )
            self._log("Registered system shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unregister_system_shared_memory(self, name="", headers=None,
                                        client_timeout=None):
        try:
            for stub in self._fleet_stubs():
                stub.SystemSharedMemoryUnregister(
                    pb.SystemSharedMemoryUnregisterRequest(name=name),
                    metadata=self._metadata(headers),
                    timeout=client_timeout,
                )
            self._log("Unregistered system shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        try:
            response = self._client_stub.TpuSharedMemoryStatus(
                pb.TpuSharedMemoryStatusRequest(name=region_name),
                metadata=self._metadata(headers),
                timeout=client_timeout,
            )
            return _maybe_json(response, as_json)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None,
        client_timeout=None
    ):
        """Register a TPU HBM region by its serialized handle (the TPU
        analogue of register_cuda_shared_memory, reference
        grpc/_client.py:1339)."""
        try:
            for stub in self._fleet_stubs():
                stub.TpuSharedMemoryRegister(
                    pb.TpuSharedMemoryRegisterRequest(
                        name=name,
                        raw_handle=raw_handle,
                        device_id=device_id,
                        byte_size=byte_size,
                    ),
                    metadata=self._metadata(headers),
                    timeout=client_timeout,
                )
            self._log("Registered TPU shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    def unregister_tpu_shared_memory(self, name="", headers=None,
                                     client_timeout=None):
        try:
            for stub in self._fleet_stubs():
                stub.TpuSharedMemoryUnregister(
                    pb.TpuSharedMemoryUnregisterRequest(name=name),
                    metadata=self._metadata(headers),
                    timeout=client_timeout,
                )
            self._log("Unregistered TPU shared memory with name '%s'" % name)
        except grpc.RpcError as e:
            raise_error_grpc(e)

    # Drop-in aliases for code migrating from the CUDA client.
    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- inference -------------------------------------------------------

    def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[dict] = None,
    ) -> InferResult:
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        client_span = None
        if self._tracer is not None:
            client_span = self._tracer.start_span(
                "client_infer", model_name, request_id, headers)
            client_span.attrs["transport"] = "grpc"
            headers = client_span.inject(headers)
        metadata = self._metadata(headers)
        compression = _grpc_compression(compression_algorithm)

        def _call(stub, remaining: Optional[float]) -> InferResult:
            # `remaining` is the shrinking share of client_timeout left
            # for this attempt (None = no deadline).
            try:
                response = stub.ModelInfer(
                    request,
                    metadata=metadata,
                    timeout=remaining,
                    compression=compression,
                )
                return InferResult(response)
            except grpc.RpcError as e:
                raise_error_grpc(e)

        def _issue() -> InferResult:
            if self._endpoint_pool is not None:
                from client_tpu.robust import call_with_retry_pool

                return call_with_retry_pool(
                    lambda state, remaining: _call(self._stubs[state.url],
                                                   remaining),
                    self._endpoint_pool, self._retry_policy,
                    deadline_s=client_timeout, sequence_id=sequence_id,
                    sequence_end=sequence_end,
                )

            from client_tpu.robust import call_with_retry

            return call_with_retry(
                lambda remaining: _call(self._client_stub, remaining),
                self._retry_policy, self._breaker,
                deadline_s=client_timeout,
            )

        if client_span is None:
            return _issue()
        try:
            result = _issue()
        except BaseException as e:
            client_span.finish(e)
            raise
        client_span.finish()
        return result

    def async_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        callback: Callable,
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        compression_algorithm: Optional[str] = None,
        parameters: Optional[dict] = None,
    ) -> CallContext:
        """Issue the request without blocking; ``callback(result,
        error)`` fires on the grpc completion thread. Returns a
        :class:`CallContext` whose ``cancel()`` aborts the call."""
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )

        # Pool routing for the callback API: one endpoint is chosen
        # least-outstanding up front and the outcome settles its
        # breaker/EWMA. Retries/hedges need a blocking wait — use
        # infer() (possibly on a worker thread) for those semantics.
        pool = self._endpoint_pool
        state = None
        stub = self._client_stub
        if pool is not None:
            state = pool.pick(sequence_id=sequence_id)
            state.breaker.before_call()
            stub = self._stubs[state.url]
            pool.note_start(state)
        import time as _time

        started = _time.monotonic()

        def _done(call_future):
            error = None
            try:
                result = InferResult(call_future.result())
            except grpc.RpcError as rpc_error:
                result, error = None, get_error_grpc(rpc_error)
            except grpc.FutureCancelledError:
                result, error = None, InferenceServerException(
                    "request cancelled", status="CANCELLED")
            except Exception as e:
                result, error = None, InferenceServerException(str(e))
            if pool is not None:
                pool.note_end(state, _time.monotonic() - started,
                              error=error)
            callback(result, error)

        context = CallContext()
        try:
            call_future = stub.ModelInfer.future(
                request,
                metadata=self._metadata(headers),
                timeout=client_timeout,
                compression=_grpc_compression(compression_algorithm),
            )
        except BaseException as e:
            # Submission itself failed (closed channel, plugin hook
            # raised): _done never runs, so settle the pool here — an
            # unreleased outstanding count would skew routing forever,
            # and an unresolved half-open probe would lock the
            # endpoint out.
            if pool is not None:
                pool.note_end(state, _time.monotonic() - started, error=e)
            raise
        context._set_call(call_future)
        call_future.add_done_callback(_done)
        return context

    # -- streaming -------------------------------------------------------

    def start_stream(
        self,
        callback: Callable,
        stream_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
    ):
        """Open the bidi ModelStreamInfer stream; every response (or
        error) is delivered to ``callback(result, error)``."""
        if self._stream is not None:
            raise_error("stream is already running; call stop_stream first")
        self._stream = _InferStream(callback, self._verbose)
        self._stream.start(self._client_stub, self._metadata(headers), stream_timeout)

    def stop_stream(self, cancel_requests: bool = False):
        if self._stream is not None:
            self._stream.close(cancel_requests)
            self._stream = None

    def async_stream_infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        enable_empty_final_response: bool = False,
        parameters: Optional[dict] = None,
    ):
        if self._stream is None:
            raise_error("stream is not running; call start_stream first")
        request = get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            outputs=outputs,
            request_id=request_id,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        if enable_empty_final_response:
            request.parameters["triton_enable_empty_final_response"].bool_param = True
        self._stream.enqueue_request(request)


def _maybe_json(message, as_json: bool):
    if as_json:
        return json_format.MessageToDict(message, preserving_proto_field_name=True)
    return message


def _grpc_compression(algorithm: Optional[str]):
    if algorithm is None or algorithm == "none":
        return None
    if algorithm == "deflate":
        return grpc.Compression.Deflate
    if algorithm == "gzip":
        return grpc.Compression.Gzip
    raise_error("unsupported compression algorithm %s" % algorithm)
