"""asyncio gRPC client — mirror of client_tpu.grpc for event-loop
applications (parity: reference tritonclient.grpc.aio,
grpc/aio/__init__.py:50+)."""

from __future__ import annotations

from typing import AsyncIterator, Optional, Sequence

import grpc

from client_tpu._infer_common import InferInput, InferRequestedOutput
from client_tpu._plugin import InferenceServerClientBase
from client_tpu.grpc._client import (
    KeepAliveOptions,
    _DEFAULT_CHANNEL_OPTIONS,
    _channel_credentials,
    _make_channel,
    _metadata_from_headers,
    probe_grpc_ready,
)
from client_tpu.grpc._utils import (
    InferResult,
    get_error_grpc,
    get_inference_request,
    raise_error,
)
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.protocol.service import GRPCInferenceServiceStub
from client_tpu.utils import InferenceServerException

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "KeepAliveOptions",
]


class InferenceServerClient(InferenceServerClientBase):
    """asyncio flavor: every RPC is a coroutine; ``stream_infer``
    consumes an async iterator of requests and yields results.

    ``url`` may be a comma-separated endpoint list (or a list), or a
    shared :class:`client_tpu.robust.EndpointPool` may be passed as
    ``endpoint_pool``: ``infer`` then routes least-outstanding across
    healthy endpoints, fails over on retryable errors, and hedges
    tail-slow requests within the pool's budget; a thread-based prober
    (sync channels, off the event loop) readmits ejected endpoints.
    Streams stay pinned to the primary endpoint. With a pool,
    ``circuit_breaker`` is ignored.

    ``tracer`` (:class:`client_tpu.tracing.ClientTracer`) records a
    client-side span per ``infer`` and propagates its W3C
    ``traceparent`` as gRPC metadata (caller-supplied traceparent
    wins)."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        ssl: bool = False,
        root_certificates: Optional[str] = None,
        private_key: Optional[str] = None,
        certificate_chain: Optional[str] = None,
        creds: Optional[grpc.ChannelCredentials] = None,
        keepalive_options: Optional[KeepAliveOptions] = None,
        channel_args: Optional[list] = None,
        retry_policy=None,
        circuit_breaker=None,
        endpoint_pool=None,
        tracer=None,
    ):
        super().__init__()
        from client_tpu.robust import EndpointPool

        urls = (endpoint_pool.urls if endpoint_pool is not None
                else EndpointPool.split_url(url))
        if not urls:
            raise InferenceServerException("invalid url '%s'" % url)
        self._owns_pool = endpoint_pool is None and len(urls) > 1
        self._endpoint_pool = (endpoint_pool if endpoint_pool is not None
                               else (EndpointPool(urls) if len(urls) > 1
                                     else None))
        # client_tpu.robust wiring (same contract as the sync client):
        # infer() retries retryable statuses with backoff + jitter.
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker if self._endpoint_pool is None \
            else None
        options = list(_DEFAULT_CHANNEL_OPTIONS)
        if keepalive_options is not None:
            options += keepalive_options.channel_args()
        if channel_args is not None:
            options += list(channel_args)
        credentials = _channel_credentials(
            ssl, root_certificates, private_key, certificate_chain, creds)
        self._channels = {
            u: _make_channel(u, options, credentials, aio=True)
            for u in urls
        }
        self._stubs = {
            u: GRPCInferenceServiceStub(ch)
            for u, ch in self._channels.items()
        }
        self._channel = self._channels[urls[0]]
        self._client_stub = self._stubs[urls[0]]
        self._verbose = verbose
        self._tracer = tracer
        if self._endpoint_pool is not None:
            # The probe is SYNC and self-contained (its own short-lived
            # channel, run on the pool's prober thread): it must never
            # touch this client's event loop (the loop being wedged is
            # exactly when probing matters) and must survive this
            # client closing when the pool is shared.
            timeout = self._endpoint_pool.probe_timeout_s
            self._endpoint_pool.ensure_prober(
                lambda u, _creds=credentials: probe_grpc_ready(
                    u, _creds, timeout))

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc_value, traceback):
        await self.close()

    async def close(self):
        if self._endpoint_pool is not None and self._owns_pool:
            self._endpoint_pool.close()
        for channel in self._channels.values():
            await channel.close()

    def pool_stats(self) -> Optional[dict]:
        """EndpointPool snapshot (hedges/failovers/ejections + per-
        endpoint health); None for a single-endpoint client."""
        return (self._endpoint_pool.stats()
                if self._endpoint_pool is not None else None)

    def _metadata(self, headers):
        headers = self._call_plugin(dict(headers) if headers else {})
        return _metadata_from_headers(headers)

    def _fleet_stubs(self):
        """Every endpoint's stub — control-plane verbs that mutate
        per-replica state (shm registration, model load/unload) must
        hit the whole fleet, not just the primary."""
        return list(self._stubs.values())

    async def _call(self, method, request, headers, client_timeout=None):
        try:
            return await method(
                request, metadata=self._metadata(headers), timeout=client_timeout
            )
        except grpc.RpcError as rpc_error:
            # `from rpc_error`: preserve the transport failure as
            # __cause__ so network errors stay debuggable.
            raise get_error_grpc(rpc_error) from rpc_error

    # -- health / metadata ----------------------------------------------

    async def is_server_live(self, headers=None, client_timeout=None) -> bool:
        response = await self._call(
            self._client_stub.ServerLive, pb.ServerLiveRequest(), headers,
            client_timeout,
        )
        return response.live

    async def is_server_ready(self, headers=None, client_timeout=None) -> bool:
        response = await self._call(
            self._client_stub.ServerReady, pb.ServerReadyRequest(), headers,
            client_timeout,
        )
        return response.ready

    async def is_model_ready(self, model_name, model_version="", headers=None,
                             client_timeout=None) -> bool:
        response = await self._call(
            self._client_stub.ModelReady,
            pb.ModelReadyRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )
        return response.ready

    async def get_server_metadata(self, headers=None, client_timeout=None):
        return await self._call(
            self._client_stub.ServerMetadata, pb.ServerMetadataRequest(),
            headers, client_timeout,
        )

    async def get_model_metadata(self, model_name, model_version="",
                                 headers=None, client_timeout=None):
        return await self._call(
            self._client_stub.ModelMetadata,
            pb.ModelMetadataRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )

    async def get_model_config(self, model_name, model_version="",
                               headers=None, client_timeout=None):
        return await self._call(
            self._client_stub.ModelConfig,
            pb.ModelConfigRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )

    async def get_model_repository_index(self, headers=None,
                                         client_timeout=None):
        return await self._call(
            self._client_stub.RepositoryIndex, pb.RepositoryIndexRequest(),
            headers, client_timeout,
        )

    async def load_model(self, model_name, headers=None, config=None,
                         client_timeout=None):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        for stub in self._fleet_stubs():
            await self._call(stub.RepositoryModelLoad, request,
                             headers, client_timeout)

    async def unload_model(self, model_name, headers=None,
                           client_timeout=None):
        for stub in self._fleet_stubs():
            await self._call(
                stub.RepositoryModelUnload,
                pb.RepositoryModelUnloadRequest(model_name=model_name),
                headers, client_timeout,
            )

    async def get_inference_statistics(self, model_name="", model_version="",
                                       headers=None, client_timeout=None):
        return await self._call(
            self._client_stub.ModelStatistics,
            pb.ModelStatisticsRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )

    # -- trace / log settings --------------------------------------------

    async def update_trace_settings(self, model_name="", settings=None,
                                    headers=None, client_timeout=None):
        """Asyncio mirror of the sync client's trace-settings update
        (parity: reference grpc/aio/__init__.py update_trace_settings)."""
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                request.settings[key]  # noqa: B018 — clears the setting
            elif isinstance(value, (list, tuple)):
                request.settings[key].value.extend(str(v) for v in value)
            else:
                request.settings[key].value.append(str(value))
        return await self._call(self._client_stub.TraceSetting, request,
                                headers, client_timeout)

    async def get_trace_settings(self, model_name="", headers=None,
                                 client_timeout=None):
        """Pure read: sends a TraceSettingRequest with the settings map
        untouched (never routed through the update path, so no server
        implementation can mistake it for a write — parity: reference
        grpc/aio/__init__.py get_trace_settings)."""
        return await self._call(
            self._client_stub.TraceSetting,
            pb.TraceSettingRequest(model_name=model_name or ""),
            headers, client_timeout)

    async def update_log_settings(self, settings, headers=None,
                                  client_timeout=None):
        request = pb.LogSettingsRequest()
        for key, value in (settings or {}).items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            else:
                request.settings[key].string_param = str(value)
        return await self._call(self._client_stub.LogSettings, request,
                                headers, client_timeout)

    async def get_log_settings(self, headers=None, client_timeout=None):
        """Pure read (see get_trace_settings)."""
        return await self._call(self._client_stub.LogSettings,
                                pb.LogSettingsRequest(),
                                headers, client_timeout)

    # -- shared memory ---------------------------------------------------

    async def get_system_shared_memory_status(self, region_name="",
                                              headers=None,
                                              client_timeout=None):
        return await self._call(
            self._client_stub.SystemSharedMemoryStatus,
            pb.SystemSharedMemoryStatusRequest(name=region_name), headers,
            client_timeout,
        )

    async def register_system_shared_memory(self, name, key, byte_size,
                                            offset=0, headers=None,
                                            client_timeout=None):
        for stub in self._fleet_stubs():
            await self._call(
                stub.SystemSharedMemoryRegister,
                pb.SystemSharedMemoryRegisterRequest(
                    name=name, key=key, offset=offset, byte_size=byte_size
                ),
                headers, client_timeout,
            )

    async def unregister_system_shared_memory(self, name="", headers=None,
                                              client_timeout=None):
        for stub in self._fleet_stubs():
            await self._call(
                stub.SystemSharedMemoryUnregister,
                pb.SystemSharedMemoryUnregisterRequest(name=name), headers,
                client_timeout,
            )

    async def get_tpu_shared_memory_status(self, region_name="", headers=None,
                                           client_timeout=None):
        return await self._call(
            self._client_stub.TpuSharedMemoryStatus,
            pb.TpuSharedMemoryStatusRequest(name=region_name), headers,
            client_timeout,
        )

    async def register_tpu_shared_memory(self, name, raw_handle, device_id,
                                         byte_size, headers=None,
                                         client_timeout=None):
        for stub in self._fleet_stubs():
            await self._call(
                stub.TpuSharedMemoryRegister,
                pb.TpuSharedMemoryRegisterRequest(
                    name=name, raw_handle=raw_handle, device_id=device_id,
                    byte_size=byte_size,
                ),
                headers, client_timeout,
            )

    async def unregister_tpu_shared_memory(self, name="", headers=None,
                                           client_timeout=None):
        for stub in self._fleet_stubs():
            await self._call(
                stub.TpuSharedMemoryUnregister,
                pb.TpuSharedMemoryUnregisterRequest(name=name), headers,
                client_timeout,
            )

    get_cuda_shared_memory_status = get_tpu_shared_memory_status
    register_cuda_shared_memory = register_tpu_shared_memory
    unregister_cuda_shared_memory = unregister_tpu_shared_memory

    # -- inference -------------------------------------------------------

    async def infer(
        self,
        model_name: str,
        inputs: Sequence[InferInput],
        model_version: str = "",
        outputs: Optional[Sequence[InferRequestedOutput]] = None,
        request_id: str = "",
        sequence_id: int = 0,
        sequence_start: bool = False,
        sequence_end: bool = False,
        priority: int = 0,
        timeout: Optional[int] = None,
        client_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
        parameters: Optional[dict] = None,
    ) -> InferResult:
        request = get_inference_request(
            model_name=model_name, inputs=inputs, model_version=model_version,
            outputs=outputs, request_id=request_id, sequence_id=sequence_id,
            sequence_start=sequence_start, sequence_end=sequence_end,
            priority=priority, timeout=timeout, parameters=parameters,
        )
        client_span = None
        if self._tracer is not None:
            client_span = self._tracer.start_span(
                "client_infer", model_name, request_id, headers)
            client_span.attrs["transport"] = "grpc-aio"
            headers = client_span.inject(headers)

        async def _issue():
            if self._endpoint_pool is not None:
                from client_tpu.robust import call_with_retry_pool_async

                async def _pool_attempt(state, remaining):
                    response = await self._call(
                        self._stubs[state.url].ModelInfer, request, headers,
                        remaining
                    )
                    return InferResult(response)

                return await call_with_retry_pool_async(
                    _pool_attempt, self._endpoint_pool, self._retry_policy,
                    deadline_s=client_timeout, sequence_id=sequence_id,
                    sequence_end=sequence_end,
                )

            async def _attempt(remaining):
                response = await self._call(
                    self._client_stub.ModelInfer, request, headers, remaining
                )
                return InferResult(response)

            from client_tpu.robust import call_with_retry_async

            return await call_with_retry_async(
                _attempt, self._retry_policy, self._breaker,
                deadline_s=client_timeout,
            )

        if client_span is None:
            return await _issue()
        try:
            result = await _issue()
        except BaseException as e:
            client_span.finish(e)
            raise
        client_span.finish()
        return result

    async def stream_infer(
        self,
        inputs_iterator: AsyncIterator[dict],
        stream_timeout: Optional[float] = None,
        headers: Optional[dict] = None,
    ):
        """Bidi streaming: consumes an async iterator of infer-call
        kwargs dicts (same keys as :meth:`infer`), yields
        (InferResult, error) tuples as responses arrive."""

        async def _requests():
            async for kwargs in inputs_iterator:
                enable_empty_final = kwargs.pop(
                    "enable_empty_final_response", False
                )
                request = get_inference_request(**kwargs)
                if enable_empty_final:
                    request.parameters[
                        "triton_enable_empty_final_response"
                    ].bool_param = True
                yield request

        try:
            stream = self._client_stub.ModelStreamInfer(
                _requests(), metadata=self._metadata(headers),
                timeout=stream_timeout,
            )
            async for response in stream:
                if response.error_message:
                    yield None, InferenceServerException(response.error_message)
                else:
                    yield InferResult(response.infer_response), None
        except grpc.RpcError as rpc_error:
            raise get_error_grpc(rpc_error) from None
