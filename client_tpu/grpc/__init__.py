"""KServe-v2 gRPC client (sync + callback-async + decoupled bidi
streaming). ``client_tpu.grpc.aio`` holds the asyncio mirror."""

from client_tpu._infer_common import InferInput, InferRequestedOutput  # noqa: F401
from client_tpu._plugin import (  # noqa: F401
    BasicAuth,
    InferenceServerClientPlugin,
    Request,
)
from client_tpu.grpc._client import (  # noqa: F401
    CallContext,
    InferenceServerClient,
    KeepAliveOptions,
)
from client_tpu.grpc._utils import InferResult  # noqa: F401
from client_tpu.robust import CircuitBreaker, RetryPolicy  # noqa: F401
from client_tpu.utils import InferenceServerException  # noqa: F401
