"""gRPC transport helpers: error mapping, proto assembly, result
wrapper. Parity surface: reference tritonclient/grpc/_utils.py and
_infer_result semantics."""

from __future__ import annotations

from typing import Optional, Sequence

import grpc
import numpy as np

from client_tpu._infer_common import (
    InferInput,
    InferRequestedOutput,
    build_request_parameters,
)
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


def get_error_grpc(rpc_error: grpc.RpcError) -> InferenceServerException:
    try:
        code = rpc_error.code().name
        details = rpc_error.details()
    except Exception:  # not a Call object
        code = None
        details = str(rpc_error)
    error = InferenceServerException(msg=details, status=code,
                                     debug_details=rpc_error)
    # Server-advised backoff rides the trailing metadata (the gRPC twin
    # of the HTTP Retry-After header); RetryPolicy sleeps at least this
    # long before the next attempt.
    try:
        for key, value in (rpc_error.trailing_metadata() or ()):
            if str(key).lower() == "retry-after":
                error.retry_after_s = float(value)
                break
    except Exception:  # noqa: BLE001 — metadata is advisory only
        pass
    return error


def raise_error_grpc(rpc_error: grpc.RpcError):
    # `from rpc_error`: keep the RpcError as __cause__ so transport
    # failures stay debuggable end to end (the traceback shows the
    # channel state, not just our wrapper).
    raise get_error_grpc(rpc_error) from rpc_error


def raise_error(msg: str):
    raise InferenceServerException(msg=msg) from None


def set_parameter(param: pb.InferParameter, value) -> None:
    if isinstance(value, bool):
        param.bool_param = value
    elif isinstance(value, int):
        param.int64_param = value
    elif isinstance(value, float):
        param.double_param = value
    elif isinstance(value, str):
        param.string_param = value
    else:
        raise_error("unsupported parameter type %s" % type(value).__name__)


def parameter_value(param: pb.InferParameter):
    which = param.WhichOneof("parameter_choice")
    return getattr(param, which) if which else None


def get_inference_request(
    model_name: str,
    inputs: Sequence[InferInput],
    model_version: str = "",
    outputs: Optional[Sequence[InferRequestedOutput]] = None,
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[dict] = None,
) -> pb.ModelInferRequest:
    """Assemble a ModelInferRequest proto. Tensor data travels in
    ``raw_input_contents`` (one bytes blob per non-shm input, in input
    order), shared-memory inputs as region parameters — the same wire
    convention as the reference (grpc_client.cc:1419-1580)."""
    request = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version
    )
    if request_id:
        request.id = request_id
    params = build_request_parameters(
        sequence_id=sequence_id,
        sequence_start=sequence_start,
        sequence_end=sequence_end,
        priority=priority,
        timeout=timeout,
        parameters=parameters,
    )
    for key, value in params.items():
        set_parameter(request.parameters[key], value)

    for infer_input in inputs:
        infer_input.validate()
        tensor = request.inputs.add()
        tensor.name = infer_input.name()
        tensor.datatype = infer_input.datatype()
        tensor.shape.extend(infer_input.shape())
        for key, value in infer_input.parameters().items():
            set_parameter(tensor.parameters[key], value)
        shm = infer_input.shared_memory()
        if shm is not None:
            region, byte_size, offset = shm
            tensor.parameters["shared_memory_region"].string_param = region
            tensor.parameters["shared_memory_byte_size"].int64_param = byte_size
            if offset:
                tensor.parameters["shared_memory_offset"].int64_param = offset
        else:
            request.raw_input_contents.append(infer_input.raw_data())

    if outputs:
        for infer_output in outputs:
            tensor = request.outputs.add()
            tensor.name = infer_output.name()
            for key, value in infer_output.parameters().items():
                set_parameter(tensor.parameters[key], value)
            if infer_output.class_count():
                tensor.parameters["classification"].int64_param = (
                    infer_output.class_count()
                )
            shm = infer_output.shared_memory()
            if shm is not None:
                region, byte_size, offset = shm
                tensor.parameters["shared_memory_region"].string_param = region
                tensor.parameters["shared_memory_byte_size"].int64_param = byte_size
                if offset:
                    tensor.parameters["shared_memory_offset"].int64_param = offset
    return request


class InferResult:
    """Result wrapper over a ModelInferResponse."""

    def __init__(self, response: pb.ModelInferResponse):
        self._response = response
        # map output name -> (tensor, raw index or None)
        self._index = {}
        raw_idx = 0
        for tensor in response.outputs:
            if "shared_memory_region" in tensor.parameters:
                self._index[tensor.name] = (tensor, None)
            else:
                idx = raw_idx if raw_idx < len(response.raw_output_contents) else None
                self._index[tensor.name] = (tensor, idx)
                raw_idx += 1

    @classmethod
    def from_response(cls, response) -> "InferResult":
        return cls(response)

    def get_response(self) -> pb.ModelInferResponse:
        return self._response

    def get_output(self, name: str):
        """The InferOutputTensor proto for ``name`` or None."""
        entry = self._index.get(name)
        return entry[0] if entry else None

    def as_numpy(self, name: str) -> Optional[np.ndarray]:
        """Decode output ``name`` to numpy. Returns None for outputs
        living in shared memory (read them via the region API)."""
        entry = self._index.get(name)
        if entry is None:
            return None
        tensor, raw_idx = entry
        if raw_idx is None:
            return None
        shape = [int(d) for d in tensor.shape]
        raw = self._response.raw_output_contents[raw_idx]
        if tensor.datatype == "BYTES":
            return deserialize_bytes_tensor(raw).reshape(shape)
        if tensor.datatype == "BF16":
            return deserialize_bf16_tensor(raw).reshape(shape)
        np_dtype = triton_to_np_dtype(tensor.datatype)
        if np_dtype is None:
            raise InferenceServerException(
                "unknown output datatype %s" % tensor.datatype
            )
        return np.frombuffer(raw, dtype=np_dtype).reshape(shape)

    def get_parameters(self) -> dict:
        return {k: parameter_value(v) for k, v in self._response.parameters.items()}
