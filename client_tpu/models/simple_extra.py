"""Protocol-conformance models backing the examples suite: BYTES
string math, stateful sequence accumulation, and a decoupled repeat
streamer — the TPU-framework counterparts of the reference's
`simple_string`, `simple_sequence`-style, and `repeat_int32` test
models (driven by e.g. reference
src/python/examples/simple_grpc_string_infer_client.py,
simple_grpc_sequence_stream_infer_client.py, and the decoupled
examples)."""

from __future__ import annotations

import threading
from typing import Dict, Iterator, Optional

import numpy as np

from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class StringAddSub(ServedModel):
    """BYTES add/sub: inputs hold decimal integer strings; outputs are
    their sums/differences as strings (parity: the reference server's
    simple_string model)."""

    def __init__(self, name: str = "simple_string", count: int = 16):
        super().__init__()
        self.name = name
        self.platform = "python"
        self._count = count
        self.inputs = [
            TensorSpec("INPUT0", "BYTES", [count]),
            TensorSpec("INPUT1", "BYTES", [count]),
        ]
        self.outputs = [
            TensorSpec("OUTPUT0", "BYTES", [count]),
            TensorSpec("OUTPUT1", "BYTES", [count]),
        ]

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        def to_ints(array: np.ndarray) -> np.ndarray:
            flat = array.reshape(-1)
            try:
                return np.array(
                    [int(v.decode() if isinstance(v, bytes) else v)
                     for v in flat],
                    dtype=np.int64,
                )
            except ValueError as e:
                raise InferenceServerException(
                    "non-integer string tensor: %s" % e,
                    status="INVALID_ARGUMENT",
                )

        in0 = to_ints(inputs["INPUT0"])
        in1 = to_ints(inputs["INPUT1"])
        out0 = np.array([str(v).encode() for v in in0 + in1],
                        dtype=np.object_)
        out1 = np.array([str(v).encode() for v in in0 - in1],
                        dtype=np.object_)
        return {"OUTPUT0": out0, "OUTPUT1": out1}


class SequenceAccumulator(ServedModel):
    """Stateful sequence model: per sequence-id running sum of the
    INT32 input; sequence_start resets, sequence_end drops the state.
    Schedules through the sequence scheduler's Direct strategy — the
    model manages its own state keyed by the sequence_* parameters, so
    the scheduler's job is slot bookkeeping, per-sequence ordering,
    and idle reclamation (parity: the simple_sequence model the
    reference sequence examples call)."""

    sequence_batching = True
    sequence_strategy = "direct"

    def __init__(self, name: str = "simple_sequence",
                 max_sequence_idle_us: int = 0,
                 max_candidate_sequences: int = 0):
        super().__init__()
        self.name = name
        self.platform = "python"
        self.max_sequence_idle_us = max_sequence_idle_us
        self.max_candidate_sequences = max_candidate_sequences
        self.inputs = [TensorSpec("INPUT", "INT32", [1])]
        self.outputs = [TensorSpec("OUTPUT", "INT32", [1])]
        self._lock = threading.Lock()
        self._state: Dict[int, int] = {}

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        params = parameters or {}
        sequence_id = int(params.get("sequence_id", 0))
        if sequence_id == 0:
            raise InferenceServerException(
                "model '%s' requires a sequence_id" % self.name,
                status="INVALID_ARGUMENT",
            )
        value = int(np.asarray(inputs["INPUT"]).reshape(-1)[0])
        with self._lock:
            if params.get("sequence_start"):
                self._state[sequence_id] = 0
            if sequence_id not in self._state:
                raise InferenceServerException(
                    "sequence %d not started" % sequence_id,
                    status="INVALID_ARGUMENT",
                )
            self._state[sequence_id] += value
            total = self._state[sequence_id]
            if params.get("sequence_end"):
                del self._state[sequence_id]
        return {"OUTPUT": np.array([total], dtype=np.int32)}


class DynaSequence(ServedModel):
    """Oldest-strategy sequence model with IMPLICIT state: the
    scheduler injects CORRID/START/END/READY controls and carries the
    running-sum STATE tensor between steps as a device-resident
    ``jax.Array``, and dispatches every step through the dynamic
    batcher — concurrent sequences' steps fuse into one batched
    execution (parity: the reference's dyna_sequence model, whose
    per-sequence results match simple_sequence's accumulation).

    Per batch row: ``new_state = state * (1 - START) + INPUT``;
    ``OUTPUT = new_state`` and ``STATE_OUT = new_state``. The START
    control makes restart-in-place correct even inside a fused batch.
    """

    max_batch_size = 16
    dynamic_batching = True
    preferred_batch_sizes = [4, 8]
    max_queue_delay_us = 2000
    sequence_batching = True
    sequence_strategy = "oldest"
    max_candidate_sequences = 16
    max_sequence_idle_us = 5_000_000
    sequence_controls = [
        {"name": "CORRID", "kind": "CONTROL_SEQUENCE_CORRID",
         "datatype": "UINT64"},
        {"name": "START", "kind": "CONTROL_SEQUENCE_START",
         "datatype": "INT32"},
        {"name": "END", "kind": "CONTROL_SEQUENCE_END",
         "datatype": "INT32"},
        {"name": "READY", "kind": "CONTROL_SEQUENCE_READY",
         "datatype": "INT32"},
    ]
    sequence_states = [
        {"input_name": "STATE_IN", "output_name": "STATE_OUT",
         "datatype": "INT32", "dims": (1,)},
    ]

    def __init__(self, name: str = "dyna_sequence", **overrides):
        super().__init__()
        self.name = name
        self.platform = "jax"
        for key, value in overrides.items():
            setattr(self, key, value)
        self.inputs = [TensorSpec("INPUT", "INT32", [1])]
        self.outputs = [TensorSpec("OUTPUT", "INT32", [1])]
        self._step_fn = None

    def _step(self):
        """Jitted step, compiled once per fused batch shape. The state
        buffer is donated: the previous step's HBM state is consumed
        in place by the next step's execution (donation is a no-op on
        backends that cannot alias, e.g. CPU)."""
        if self._step_fn is None:
            import jax

            def step(value, state, start):
                new_state = state * (1 - start) + value
                return new_state, new_state

            donate = (1,) if jax.default_backend() != "cpu" else ()
            self._step_fn = jax.jit(step, donate_argnums=donate)
        return self._step_fn

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        value = inputs["INPUT"]
        state = inputs.get("STATE_IN")
        start = inputs.get("START")
        if state is None or start is None:
            raise InferenceServerException(
                "model '%s' is sequence-batched: STATE_IN/START are "
                "scheduler-injected — send requests with a sequence_id"
                % self.name,
                status="INVALID_ARGUMENT",
            )
        output, new_state = self._step()(value, state, start)
        return {"OUTPUT": output, "STATE_OUT": new_state}


class RepeatInt32(ServedModel):
    """Decoupled streamer: emits one response per element of IN, with
    an optional per-response DELAY (us) — the shape the reference's
    decoupled examples drive (repeat_int32)."""

    decoupled = True

    def __init__(self, name: str = "repeat_int32"):
        super().__init__()
        self.name = name
        self.platform = "python"
        self.inputs = [
            TensorSpec("IN", "INT32", [-1]),
            TensorSpec("DELAY", "UINT32", [-1], optional=True),
        ]
        self.outputs = [TensorSpec("OUT", "INT32", [1])]

    def infer_stream(self, inputs: Dict[str, np.ndarray],
                     parameters: Optional[dict] = None
                     ) -> Iterator[Dict[str, np.ndarray]]:
        import time

        values = np.asarray(inputs["IN"]).reshape(-1)
        delays = None
        if "DELAY" in inputs:
            delays = np.asarray(inputs["DELAY"]).reshape(-1)
        for i, value in enumerate(values):
            if delays is not None and i < len(delays):
                time.sleep(int(delays[i]) / 1e6)
            yield {"OUT": np.array([value], dtype=np.int32)}
