"""JAX model zoo served by the reference server and used by the
benchmark configs (BASELINE.md). Each entry maps a model name to a
zero-argument factory, consumed by the ModelRepository."""

from __future__ import annotations

from typing import Callable, Dict

from client_tpu.server.model import ServedModel


def builtin_model_factories() -> Dict[str, Callable[[], ServedModel]]:
    from client_tpu.models.add_sub import AddSub

    factories: Dict[str, Callable[[], ServedModel]] = {
        "add_sub": AddSub,
        "simple": lambda: AddSub(name="simple", datatype="INT32", shape=(16,)),
        "add_sub_fp32": lambda: AddSub(
            name="add_sub_fp32", datatype="FP32", shape=(16,)
        ),
    }
    try:
        from client_tpu.models.zoo import extra_model_factories

        factories.update(extra_model_factories())
    except ImportError:
        pass
    return factories
