"""JAX model zoo served by the reference server and used by the
benchmark configs (BASELINE.md). Each entry maps a model name to a
zero-argument factory, consumed by the ModelRepository."""

from __future__ import annotations

from typing import Callable, Dict

from client_tpu.server.model import ServedModel


def builtin_model_factories(repository=None
                            ) -> Dict[str, Callable[[], ServedModel]]:
    from client_tpu.models.add_sub import AddSub
    from client_tpu.models.simple_extra import (
        DynaSequence,
        RepeatInt32,
        SequenceAccumulator,
        StringAddSub,
    )
    from client_tpu.models.zoo import extra_model_factories

    factories: Dict[str, Callable[[], ServedModel]] = {
        "add_sub": AddSub,
        "simple": lambda: AddSub(name="simple", datatype="INT32", shape=(16,)),
        "add_sub_fp32": lambda: AddSub(
            name="add_sub_fp32", datatype="FP32", shape=(16,)
        ),
        "add_sub_int8": lambda: AddSub(
            name="add_sub_int8", datatype="INT8", shape=(16,)
        ),
        # 4 MiB per tensor: conformance ammunition for HTTP/2 flow
        # control — requests and responses must chunk through DATA
        # frames + WINDOW_UPDATEs in both directions.
        "add_sub_large": lambda: AddSub(
            name="add_sub_large", datatype="FP32", shape=(1048576,)
        ),
        "add_sub_tpu": lambda: AddSub(
            name="add_sub_tpu", datatype="FP32", shape=(16,), device="tpu"
        ),
        "simple_string": StringAddSub,
        "simple_sequence": SequenceAccumulator,
        "dyna_sequence": DynaSequence,
        "repeat_int32": RepeatInt32,
    }
    factories.update(extra_model_factories(repository))
    return factories
