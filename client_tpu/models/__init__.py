"""JAX model zoo served by the reference server and used by the
benchmark configs (BASELINE.md). Each entry maps a model name to a
zero-argument factory, consumed by the ModelRepository."""

from __future__ import annotations

from typing import Callable, Dict

from client_tpu.server.model import ServedModel


def builtin_model_factories(repository=None
                            ) -> Dict[str, Callable[[], ServedModel]]:
    from client_tpu.models.add_sub import AddSub, MultiOutLarge
    from client_tpu.models.simple_extra import (
        DynaSequence,
        RepeatInt32,
        SequenceAccumulator,
        StringAddSub,
    )
    from client_tpu.models.zoo import extra_model_factories

    def _simple_cache() -> ServedModel:
        # The `simple` model with the response cache enabled, fronted
        # by a dynamic batcher whose preferred size (8) exceeds the
        # bench harness's closed-loop concurrency — misses pay the
        # full gather window, which is exactly the latency a cache hit
        # masks (hits bypass the queue/batcher entirely).
        model = AddSub(name="simple_cache", datatype="INT32", shape=(16,))
        model.response_cache = True
        model.max_batch_size = 8
        model.dynamic_batching = True
        model.preferred_batch_sizes = [8]
        model.max_queue_delay_us = 1000
        return model

    def _simple_qos() -> ServedModel:
        # The `simple` model with two priority classes and a bounded,
        # sheddable queue — the multi-tenant QoS testbed. Bulk
        # (priority 2, the default) can saturate max_queue_size while
        # interactive priority-1 traffic overtakes at dispatch time
        # (and displaces bulk at a full queue), which is exactly what
        # the overload smoke gates on. The slow-ish gather window
        # (preferred 8 / 2 ms) makes queueing observable on CPU.
        model = AddSub(name="simple_qos", datatype="INT32", shape=(16,))
        model.max_batch_size = 8
        model.dynamic_batching = True
        model.preferred_batch_sizes = [8]
        model.max_queue_delay_us = 2000
        model.max_queue_size = 32
        model.priority_levels = 2
        model.default_priority_level = 2
        model.shed_watermark = 0.9
        return model

    def _simple_replicas() -> ServedModel:
        # The `simple` model served as an instance group of 4
        # per-device fault domains (client_tpu.server.replicas): a
        # dynamic batcher gathers fused batches, the replica router
        # spreads them by least expected completion time, and a
        # degraded replica is ejected/self-healed without dropping the
        # model from readiness. Recovery knobs are tuned tight so the
        # chaos smoke and tests observe eject -> readmit in seconds.
        model = AddSub(name="simple_replicas", datatype="INT32",
                       shape=(16,))
        model.max_batch_size = 8
        model.dynamic_batching = True
        model.preferred_batch_sizes = [4]
        model.max_queue_delay_us = 500
        model.instance_group_count = 4
        model.instance_group_kind = "cpu"
        model.replica_watchdog_us = 2_000_000
        model.replica_failure_threshold = 3
        model.replica_recovery_s = 0.5
        return model

    def _simple_slo() -> ServedModel:
        # The `simple` model with a declared SLO block + a tight
        # absolute flight-recorder threshold — the SLO-engine/flight
        # testbed (metrics_lint drives it so the tpu_slo_* families
        # render; tools/flight_smoke.py chaos-injects against it).
        # The latency target is generous for a CPU add even under a
        # contended CI host (jit-compile spikes and scheduler noise
        # stay under it, so a clean run burns ~0); chaos latency_ms
        # injection blows straight through it.
        model = AddSub(name="simple_slo", datatype="INT32", shape=(16,))
        model.slo_p99_latency_us = 50_000
        model.slo_availability = 0.999
        model.flight_slow_us = 50_000
        return model

    def _simple_autoscale() -> ServedModel:
        # The autoscale testbed: one replica at rest, growable to 4 by
        # the feedback controller (client_tpu.server.autoscale), with
        # two priority classes so the controller's shed directive has
        # a lowest class to shed and a generous-for-CPU latency SLO
        # whose burn the controller reads. Cooldowns are tuned tight
        # (0.3s up / 1s down) so tests and the autoscale smoke observe
        # grow -> shrink inside seconds; queue_high 2 means "more than
        # two gathered batches of backlog per healthy replica".
        model = AddSub(name="simple_autoscale", datatype="INT32",
                       shape=(16,))
        model.max_batch_size = 8
        model.dynamic_batching = True
        model.preferred_batch_sizes = [8]
        model.max_queue_delay_us = 500
        model.max_queue_size = 64
        model.priority_levels = 2
        model.default_priority_level = 2
        model.shed_watermark = 0.95
        model.instance_group_count = 1
        model.instance_group_kind = "cpu"
        model.replica_watchdog_us = 2_000_000
        model.replica_failure_threshold = 3
        model.replica_recovery_s = 0.5
        model.slo_p99_latency_us = 80_000
        model.slo_availability = 0.999
        model.autoscale_min_replicas = 1
        model.autoscale_max_replicas = 4
        model.autoscale_interval_s = 0.2
        model.autoscale_queue_high = 2.0
        model.autoscale_up_cooldown_s = 0.3
        model.autoscale_down_cooldown_s = 1.0
        return model

    factories: Dict[str, Callable[[], ServedModel]] = {
        "add_sub": AddSub,
        "simple": lambda: AddSub(name="simple", datatype="INT32", shape=(16,)),
        "simple_cache": _simple_cache,
        "simple_qos": _simple_qos,
        "simple_replicas": _simple_replicas,
        "simple_slo": _simple_slo,
        "simple_autoscale": _simple_autoscale,
        "add_sub_fp32": lambda: AddSub(
            name="add_sub_fp32", datatype="FP32", shape=(16,)
        ),
        "add_sub_int8": lambda: AddSub(
            name="add_sub_int8", datatype="INT8", shape=(16,)
        ),
        # 4 MiB per tensor: conformance ammunition for HTTP/2 flow
        # control — requests and responses must chunk through DATA
        # frames + WINDOW_UPDATEs in both directions.
        "add_sub_large": lambda: AddSub(
            name="add_sub_large", datatype="FP32", shape=(1048576,)
        ),
        "add_sub_tpu": lambda: AddSub(
            name="add_sub_tpu", datatype="FP32", shape=(16,), device="tpu"
        ),
        # Overlapped-vs-legacy relay-fetch A/B pair: identical
        # 4-output x 4 MiB models, one with the fetch subsystem on
        # (the default), one opted out via overlapped_fetch=False
        # (tools/fetch_smoke.py + the bench relay_fetch stage).
        "fetch_bench": lambda: MultiOutLarge(name="fetch_bench"),
        "fetch_bench_legacy": lambda: MultiOutLarge(
            name="fetch_bench_legacy", overlapped=False
        ),
        "simple_string": StringAddSub,
        "simple_sequence": SequenceAccumulator,
        "dyna_sequence": DynaSequence,
        "repeat_int32": RepeatInt32,
    }
    factories.update(extra_model_factories(repository))
    return factories
