"""Extended model zoo: the BASELINE.md benchmark models."""

from __future__ import annotations

from typing import Callable, Dict

from client_tpu.server.model import ServedModel


def extra_model_factories(repository=None) -> Dict[str, Callable[[], ServedModel]]:
    from client_tpu.models.bert import BertModel
    from client_tpu.models.ensemble import (
        AbBackboneModel,
        AbPostprocessModel,
        AbPreprocessModel,
        PostprocessModel,
        PreprocessModel,
        make_ab_ensemble,
        make_image_ensemble,
    )
    from client_tpu.models.llm import LlmConfig, LlmModel
    from client_tpu.models.resnet import ResNetModel

    factories: Dict[str, Callable[[], ServedModel]] = {
        "resnet50": ResNetModel,
        "bert_base": BertModel,
        # Paged KV cache (docs/llm_serving.md): 32 decode lanes over a
        # page pool sized at ~25% of the dense worst case
        # (lanes x max_seq) — HBM follows live tokens, and admission
        # control sheds honestly past the pool instead of OOMing.
        "llm_tiny": lambda: LlmModel(name="llm_tiny", decode_lanes=32,
                                     kv_pages=512),
        "llm_small": lambda: LlmModel(
            name="llm_small",
            cfg=LlmConfig(d_model=512, n_layers=8, n_heads=8, n_kv_heads=4,
                          d_ff=1408, max_seq=2048),
            decode_lanes=32, kv_pages=1024,
        ),
        "preprocess": PreprocessModel,
        "postprocess": PostprocessModel,
    }
    if repository is not None:
        factories["ensemble_image"] = (
            lambda: make_image_ensemble(repository)
        )
        # ensemble_dataflow_ab bench pair: identical step graphs over
        # per-arm composing models, differing only in device_dataflow.
        for suffix in ("", "_legacy"):
            factories["ab_pre" + suffix] = (
                lambda s=suffix: AbPreprocessModel("ab_pre" + s))
            factories["ab_backbone" + suffix] = (
                lambda s=suffix: AbBackboneModel("ab_backbone" + s))
            factories["ab_post" + suffix] = (
                lambda s=suffix: AbPostprocessModel("ab_post" + s))
        factories["ensemble_ab"] = (
            lambda: make_ab_ensemble(repository))
        factories["ensemble_ab_legacy"] = (
            lambda: make_ab_ensemble(repository,
                                     name="ensemble_ab_legacy",
                                     legacy=True))
    return factories
