"""Ensemble scheduling: a pipeline of composing models executed
server-side (BASELINE config #4: preprocess -> backbone ->
postprocess over decoupled streaming). The perf harness's ModelParser
reads the composing models out of the config like it does for triton
ensembles."""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from client_tpu.protocol import model_config_pb2 as mc
from client_tpu.server import tracing as spantrace
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


class PreprocessModel(ServedModel):
    """uint8 image [224,224,3] -> normalized FP32 NHWC.

    Runs ON DEVICE: the wire payload stays the compact uint8 image
    (4x smaller than fp32) and the normalized tensor is born in HBM,
    so the downstream backbone fuses DEVICE chunks across concurrent
    ensemble requests and nothing round-trips to the host between
    steps."""

    platform = "jax"
    max_batch_size = 32

    def __init__(self, name: str = "preprocess"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3])]
        self.outputs = [TensorSpec("IMAGE", "FP32", [224, 224, 3])]
        mean = np.array([0.485, 0.456, 0.406], dtype=np.float32) * 255
        std = np.array([0.229, 0.224, 0.225], dtype=np.float32) * 255
        import jax
        import jax.numpy as jnp

        mean_d, std_d = jnp.asarray(mean), jnp.asarray(std)
        self._fn = jax.jit(
            lambda raw: (raw.astype(jnp.float32) - mean_d) / std_d)

    def infer(self, inputs, parameters=None):
        return {"IMAGE": self._fn(inputs["RAW_IMAGE"])}

    def warmup(self) -> None:
        import jax
        import jax.numpy as jnp

        for batch in (1, 8, 16, 32):
            jax.block_until_ready(
                self._fn(jnp.zeros((batch, 224, 224, 3), dtype=jnp.uint8)))


class PostprocessModel(ServedModel):
    """logits -> top-1 "score:index" BYTES label."""

    platform = "jax"
    max_batch_size = 32

    def __init__(self, name: str = "postprocess", num_classes: int = 1000):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("LOGITS", "FP32", [num_classes])]
        self.outputs = [TensorSpec("LABEL", "BYTES", [1])]

    def infer(self, inputs, parameters=None):
        logits = np.asarray(inputs["LOGITS"])
        batched = logits.ndim == 2
        if not batched:
            logits = logits[None]
        idx = logits.argmax(axis=-1)
        exp = np.exp(logits - logits.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        # Vectorized "%f:%d" formatting (np.char runs the same %
        # operator element-wise, so bytes stay identical to the old
        # per-row Python loop).
        top = probs[np.arange(len(idx)), idx]
        text = np.char.add(
            np.char.add(np.char.mod("%f", top), ":"),
            np.char.mod("%d", idx))
        labels = np.char.encode(text).astype(np.object_)[:, None]
        return {"LABEL": labels if batched else labels[0]}


class DataflowContext:
    """Everything the core lends :meth:`EnsembleModel.infer_dataflow`
    for one request: span trace, telemetry, per-composing-model stats
    recording, batcher/replica resolution, and the stage-output cache
    closures (already keyed to this request's edge digest). All
    optional — a ``None`` field skips that integration."""

    __slots__ = ("trace", "telemetry", "stats_recorder", "batcher_for",
                 "target_for", "cache_lookup", "cache_insert",
                 "queue_from_ns", "cancel", "arena")

    def __init__(self, trace=None, telemetry=None, stats_recorder=None,
                 batcher_for=None, target_for=None, cache_lookup=None,
                 cache_insert=None, queue_from_ns: int = 0,
                 cancel=None, arena=None):
        self.trace = trace
        self.telemetry = telemetry
        self.stats_recorder = stats_recorder
        self.batcher_for = batcher_for
        self.target_for = target_for
        # cache_lookup(step_index, model) -> step outputs dict or None;
        # cache_insert(step_index, model, outputs). The core binds the
        # request's content digest so the executor never hashes.
        self.cache_lookup = cache_lookup
        self.cache_insert = cache_insert
        self.queue_from_ns = queue_from_ns
        # The request's CancelToken (or None): checked between
        # composing stages so a cancelled request aborts the remaining
        # subgraph, and its remaining deadline budget replaces the
        # original `timeout` in each stage's queue policy.
        self.cancel = cancel
        # The core's TpuArena (or None): interior hand-off tensors
        # land in arena regions for the request's duration, making
        # every stage boundary a pull-addressable edge (the region
        # books its own HBM row, replacing the interior lease).
        self.arena = arena


class EnsembleModel(ServedModel):
    """Executes composing models in order, wiring tensors via
    input/output maps (ensemble tensor name -> step tensor name)."""

    platform = "ensemble"
    # Device-resident dataflow (the default serving path): the core
    # executes the step graph itself, handing each stage's output —
    # still a device array — straight to the next stage's batcher.
    # False = the legacy host-mediated loop (the A/B opt-out arm,
    # PR-12 pattern), byte-identical outputs.
    device_dataflow = True

    def __init__(
        self,
        name: str,
        repository,
        steps: List[Tuple[str, Dict[str, str], Dict[str, str]]],
        inputs: List[TensorSpec],
        outputs: List[TensorSpec],
        max_batch_size: int = 0,
    ):
        super().__init__()
        self.name = name
        self._repository = repository
        self._steps = steps
        self.inputs = inputs
        self.outputs = outputs
        self.max_batch_size = max_batch_size
        # How many interior hand-offs landed in arena regions (vs the
        # lease fallback) — observability for the zero-copy edge.
        self.interior_arena_regions = 0
        # Set by the server core so composing-step executions show up
        # in per-model statistics (Triton records composing models'
        # queue/compute like top-level requests): callable
        # (model_name, count, compute_ns, executions, queue_ns).
        self.stats_recorder = None
        # Set by the server core: resolves a composing model to its
        # dynamic batcher (or None). Steps entering a batching model's
        # scheduler fuse ACROSS concurrent ensemble requests — without
        # this, every concurrent stream request runs its own batch-1
        # backbone execution and pays its own device round trip.
        self.batcher_resolver = None

    def _extend_config(self, config: mc.ModelConfig) -> None:
        for model_name, input_map, output_map in self._steps:
            step = config.ensemble_scheduling.step.add()
            step.model_name = model_name
            for ens_name, step_name in input_map.items():
                step.input_map[ens_name] = step_name
            for ens_name, step_name in output_map.items():
                step.output_map[ens_name] = step_name

    def _wire_step(self, tensors: Dict[str, np.ndarray],
                   model_name: str, input_map: Dict[str, str],
                   max_batch_size: int):
        """(step_inputs, count) for one step; raises when the graph
        references a tensor no earlier step produced."""
        step_inputs = {}
        for ens_name, step_name in input_map.items():
            if ens_name not in tensors:
                raise InferenceServerException(
                    "ensemble '%s': tensor '%s' unavailable for step "
                    "'%s'" % (self.name, ens_name, model_name),
                    status="INVALID_ARGUMENT",
                )
            step_inputs[step_name] = tensors[ens_name]
        first = next(iter(step_inputs.values()), None)
        count = (
            int(first.shape[0])
            if getattr(first, "ndim", 0) and max_batch_size > 0
            else 1
        )
        return step_inputs, count

    def infer(self, inputs, parameters=None):
        """Legacy host-mediated step loop (the ``device_dataflow=
        False`` A/B arm, and the path for an ensemble invoked outside
        a core): each stage's outputs round-trip through this caller
        before the next stage sees them."""
        tensors: Dict[str, np.ndarray] = dict(inputs)
        for model_name, input_map, output_map in self._steps:
            # load (not get): resolve composing models on demand even
            # if they were never explicitly loaded or got unloaded
            model = self._repository.load(model_name)
            step_inputs, count = self._wire_step(
                tensors, model_name, input_map, model.max_batch_size)
            batcher = self.batcher_resolver(model) \
                if self.batcher_resolver is not None else None
            if self.stats_recorder is not None:
                start_ns = time.monotonic_ns()
                if batcher is not None:
                    step_outputs, queue_ns, leader = batcher.infer(
                        step_inputs, parameters or {}, count)
                    # Triton books fused compute once, per execution:
                    # only the leader records the (queue-corrected)
                    # wall time; riders contribute their row count.
                    executions = 1 if leader else 0
                    compute_ns = max(
                        time.monotonic_ns() - start_ns - queue_ns, 0
                    ) if leader else 0
                else:
                    queue_ns = 0
                    step_outputs = model.infer(step_inputs, parameters)
                    executions = 1
                    compute_ns = time.monotonic_ns() - start_ns
                self.stats_recorder(
                    model_name, count, compute_ns, executions,
                    queue_ns=queue_ns)
            elif batcher is not None:
                step_outputs, _, _ = batcher.infer(
                    step_inputs, parameters or {}, count)
            else:
                step_outputs = model.infer(step_inputs, parameters)
            for ens_name, step_name in output_map.items():
                tensors[ens_name] = step_outputs[step_name]
        return {spec.name: tensors[spec.name] for spec in self.outputs}

    def infer_dataflow(self, inputs, parameters, ctx: DataflowContext):
        """Device-resident dataflow execution (the core's serving
        path): stage outputs are handed to the next stage's batcher
        as-is — device arrays stay device arrays, host encode happens
        only at the graph edge (the core's output fetch). Returns
        ``(outputs, queue_ns_total)`` where ``queue_ns_total`` is the
        summed interior batcher queue time (the ensemble's own stats
        book it as queue, mirroring the batcher path).

        Per stage: fuse through the composing model's dynamic batcher
        when it has one (``device_outputs=True`` — the member wakes
        with device slices at compute end, and fuses with concurrent
        ensembles AND standalone wire traffic for the same model);
        otherwise execute directly on the core's execution target
        (the PR-8 ReplicaSet proxy when replicated, so replica fault
        masking covers ensemble steps). A composing-model response-
        cache hit short-circuits the whole prefix subgraph: the lookup
        scans deepest-first and resumes execution past the hit."""
        tensors: Dict[str, np.ndarray] = dict(inputs)
        params = parameters or {}
        steps = self._steps
        start_index = 0
        mark = ctx.queue_from_ns or time.monotonic_ns()
        if ctx.cache_lookup is not None:
            for k in range(len(steps) - 1, -1, -1):
                model_name, _, output_map = steps[k]
                model = self._repository.load(model_name)
                cached = ctx.cache_lookup(k, model)
                if cached is None:
                    continue
                mapped = {ens_name: step_name
                          for ens_name, step_name in output_map.items()
                          if step_name in cached}
                if not self._resumable_after(k, set(tensors)
                                             | set(mapped)):
                    # A later stage (or the ensemble's own outputs)
                    # needs a tensor this hit would strand — keep
                    # scanning for a shallower one.
                    continue
                for ens_name, step_name in mapped.items():
                    tensors[ens_name] = cached[step_name]
                start_index = k + 1
                now = time.monotonic_ns()
                if ctx.trace is not None:
                    ctx.trace.add_timed(
                        spantrace.SPAN_ENSEMBLE_STEP, mark, now,
                        {"step": "%d:%s" % (k, model_name),
                         "cache_hit": True})
                mark = now
                break
        queue_ns_total = 0
        # Interior hand-offs live on device between stages. Preferred
        # landing: a TPU arena region per stage boundary (the region's
        # own `arena/regions` HBM row covers the bytes, and the stage
        # edge becomes pull-addressable — a downstream consumer on
        # another host could redeem the segments over the DCN pull
        # path with no host round-trip on this side). Fallback when
        # the arena is absent or landing fails: the PR-16 best-effort
        # `ensemble_interior` lease. Both are accounting/addressing —
        # never a serving dependency.
        allocator = self._interior_allocator()
        interior_leases = []
        interior_regions = []
        try:
            for k in range(start_index, len(steps)):
                step_params = params
                if ctx.cancel is not None:
                    # Stage boundary: abort the remaining subgraph the
                    # moment the caller is gone (work already done for
                    # earlier stages may still populate the composing
                    # cache — it was paid for and is reusable).
                    ctx.cancel.raise_if_cancelled("ensemble")
                    remaining = ctx.cancel.remaining_us()
                    if remaining is not None:
                        # Each stage gets the REMAINING deadline budget
                        # (deadline minus elapsed), not the original
                        # timeout — a deep graph must not overshoot its
                        # caller's deadline by N x stages.
                        step_params = dict(params)
                        step_params["timeout"] = remaining
                model_name, input_map, output_map = steps[k]
                model = self._repository.load(model_name)
                step_inputs, count = self._wire_step(
                    tensors, model_name, input_map, model.max_batch_size)
                batcher = ctx.batcher_for(model) \
                    if ctx.batcher_for is not None else None
                queue_ns = 0
                executions = 1
                if batcher is not None and "sequence_id" not in params:
                    step_outputs, queue_ns, leader = batcher.infer(
                        step_inputs, step_params, count, trace=ctx.trace,
                        queue_from_ns=mark, device_outputs=True,
                        cancel=ctx.cancel)
                    executions = 1 if leader else 0
                    if not leader and ctx.telemetry is not None:
                        ctx.telemetry.record_ensemble_fused(self.name)
                else:
                    target = (ctx.target_for(model)
                              if ctx.target_for is not None else model)
                    step_outputs = target.infer(step_inputs, step_params)
                end = time.monotonic_ns()
                queue_ns_total += queue_ns
                if ctx.stats_recorder is not None:
                    compute_ns = (max(end - mark - queue_ns, 0)
                                  if executions else 0)
                    ctx.stats_recorder(model_name, count, compute_ns,
                                       executions, queue_ns=queue_ns)
                step_label = "%d:%s" % (k, model_name)
                if ctx.trace is not None:
                    ctx.trace.add_timed(
                        spantrace.SPAN_ENSEMBLE_STEP, mark, end,
                        {"step": step_label, "batch": count,
                         "fused": executions == 0})
                if ctx.telemetry is not None:
                    ctx.telemetry.observe_ensemble_step(
                        self.name, step_label, (end - mark) / 1000.0,
                        spantrace.exemplar_id(ctx.trace))
                if ctx.cache_insert is not None:
                    ctx.cache_insert(k, model, step_outputs)
                for ens_name, step_name in output_map.items():
                    tensors[ens_name] = step_outputs[step_name]
                if k < len(steps) - 1 and (ctx.arena is not None
                                           or allocator is not None):
                    nbytes = self._device_hand_off_bytes(step_outputs)
                    if nbytes > 0:
                        region_id = (
                            self._land_interior(ctx.arena, step_outputs,
                                                nbytes)
                            if ctx.arena is not None else None)
                        if region_id is not None:
                            interior_regions.append(region_id)
                            self.interior_arena_regions += 1
                        elif allocator is not None:
                            interior_leases.append(allocator.lease(
                                self.name, "ensemble_interior", nbytes,
                                best_effort=True))
                mark = end
            return ({spec.name: tensors[spec.name]
                     for spec in self.outputs}, queue_ns_total)
        finally:
            if ctx.arena is not None:
                for region_id in interior_regions:
                    try:
                        ctx.arena.destroy_region(region_id)
                    except Exception:  # noqa: BLE001 — teardown must
                        pass  # never mask the stage result
            if allocator is not None:
                for interior in interior_leases:
                    allocator.release(interior)

    @staticmethod
    def _land_interior(arena, step_outputs, nbytes: int):
        """Land a stage's device-resident outputs in one arena region:
        segments are adopted at packed offsets with their wire dtype,
        so the whole hand-off is addressable through the arena's pull
        path. Returns the region_id, or None on any failure (the
        caller falls back to the plain interior lease) — the landed
        arrays are the SAME device buffers the next stage consumes,
        adoption adds addressing, not a copy."""
        try:
            handle = arena.create_region(nbytes)
            region_id = json.loads(handle)["region_id"]
        except Exception:  # noqa: BLE001 — arena full / no devices
            return None
        try:
            from client_tpu.server import fetch
            from client_tpu.utils import np_to_wire_dtype

            offset = 0
            for name in sorted(step_outputs):
                value = step_outputs[name]
                if not (fetch.is_device_value(value)
                        and not fetch.host_committed(value)):
                    continue
                seg_bytes = int(getattr(value, "nbytes", 0))
                if seg_bytes <= 0:
                    continue
                try:
                    datatype = np_to_wire_dtype(np.dtype(value.dtype))
                except Exception:  # noqa: BLE001 — exotic dtype
                    datatype = None
                arena.adopt_segment(
                    region_id, offset, seg_bytes, datatype,
                    list(getattr(value, "shape", ()) or ()), value)
                offset += seg_bytes
            return region_id
        except Exception:  # noqa: BLE001 — partial landing: drop the
            try:  # region so its HBM row never outlives the request
                arena.destroy_region(region_id)
            except Exception:  # noqa: BLE001
                pass
            return None

    @staticmethod
    def _interior_allocator():
        """The process-wide HBM allocator (None when the server layer
        is unavailable) — interior hand-off tracking is best-effort
        accounting, never a serving dependency."""
        try:
            from client_tpu.server import hbm

            return hbm.get()
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _device_hand_off_bytes(step_outputs) -> int:
        """Bytes of a stage's outputs that stay device-resident into
        the next stage (host-committed arrays cost no HBM)."""
        try:
            from client_tpu.server import fetch

            return sum(
                int(getattr(value, "nbytes", 0))
                for value in step_outputs.values()
                if fetch.is_device_value(value)
                and not fetch.host_committed(value))
        except Exception:  # noqa: BLE001
            return 0

    def _resumable_after(self, k: int, available: set) -> bool:
        """True when execution can resume at step ``k + 1`` with only
        ``available`` ensemble tensors in hand: every later stage's
        inputs and every ensemble output stays reachable."""
        avail = set(available)
        for j in range(k + 1, len(self._steps)):
            _, input_map, output_map = self._steps[j]
            if any(ens_name not in avail for ens_name in input_map):
                return False
            avail.update(output_map)
        return all(spec.name in avail for spec in self.outputs)

    def warmup(self) -> None:
        for model_name, _, _ in self._steps:
            self._repository.load(model_name).warmup()


def make_image_ensemble(repository, name: str = "ensemble_image",
                        backbone: str = "resnet50") -> EnsembleModel:
    """preprocess -> resnet -> postprocess with triton-style maps."""
    ensemble = EnsembleModel(
        name=name,
        repository=repository,
        steps=[
            ("preprocess", {"RAW_IMAGE": "RAW_IMAGE"}, {"image": "IMAGE"}),
            (backbone, {"image": "INPUT"}, {"logits": "OUTPUT"}),
            ("postprocess", {"logits": "LOGITS"}, {"LABEL": "LABEL"}),
        ],
        inputs=[TensorSpec("RAW_IMAGE", "UINT8", [224, 224, 3])],
        outputs=[TensorSpec("LABEL", "BYTES", [1])],
        max_batch_size=32,
    )
    # Fuse concurrent ensemble requests BEFORE the first device hop:
    # per-request image upload + logits fetch through the relay cap a
    # request-at-a-time pipeline at ~80/s regardless of server design
    # (each small transfer serializes ~12 ms in the relay), while a
    # fused bucket pays ONE upload and ONE fetch for the whole batch.
    # The 20 ms gather window (measured: 5 ms only reached ~4-wide
    # buckets under continuous streaming load; 20 ms reaches ~15 and
    # is small next to the bucket's ~150 ms pipeline) lets a response
    # burst's re-sends re-converge into the next bucket.
    ensemble.dynamic_batching = True
    ensemble.preferred_batch_sizes = [8, 16, 32]
    ensemble.max_queue_delay_us = 20000
    return ensemble


# -- dataflow A/B bench pair --------------------------------------------
#
# A three-step ensemble whose middle stage has a cost PROPORTIONAL to
# batch rows (a sleep per row plus a deterministic matmul): fusion
# cannot amortize it, so the measured gap between the arms isolates
# what the dataflow actually changes — per-stage batching and the
# composing-cache short-circuit (the legacy loop pays backbone compute
# on every request; the PR-5 caveat meant it could never legally use
# the composing cache).

AB_BACKBONE_ROW_COST_S = 0.0025


class AbPreprocessModel(ServedModel):
    """Host-side scale stage for the dataflow A/B pair (direct step,
    no scheduler)."""

    max_batch_size = 32

    def __init__(self, name: str = "ab_pre"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("RAW", "FP32", [8])]
        self.outputs = [TensorSpec("SCALED", "FP32", [8])]

    def infer(self, inputs, parameters=None):
        raw = np.asarray(inputs["RAW"], dtype=np.float32)
        return {"SCALED": raw * np.float32(1.0 / 255.0)}


class AbBackboneModel(ServedModel):
    """Batched backbone whose wall cost scales with batch rows, so the
    A/B gap measures dataflow mechanics, not batching amortization.
    ``response_cache=True`` makes it the cache-short-circuit stage."""

    max_batch_size = 32
    dynamic_batching = True
    preferred_batch_sizes = [16, 32]
    max_queue_delay_us = 3000
    response_cache = True

    def __init__(self, name: str = "ab_backbone",
                 row_cost_s: float = AB_BACKBONE_ROW_COST_S):
        super().__init__()
        self.name = name
        self._row_cost_s = row_cost_s
        rng = np.random.default_rng(1234)
        self._weights = rng.standard_normal((8, 8)).astype(np.float32)
        self.inputs = [TensorSpec("SCALED", "FP32", [8])]
        self.outputs = [TensorSpec("FEATS", "FP32", [8])]

    def infer(self, inputs, parameters=None):
        x = np.asarray(inputs["SCALED"], dtype=np.float32)
        rows = int(x.shape[0]) if x.ndim == 2 else 1
        time.sleep(self._row_cost_s * rows)
        return {"FEATS": x @ self._weights}


class AbPostprocessModel(ServedModel):
    """Trivial host reduction at the graph edge."""

    max_batch_size = 32

    def __init__(self, name: str = "ab_post"):
        super().__init__()
        self.name = name
        self.inputs = [TensorSpec("FEATS", "FP32", [8])]
        self.outputs = [TensorSpec("SCORE", "FP32", [1])]

    def infer(self, inputs, parameters=None):
        feats = np.asarray(inputs["FEATS"], dtype=np.float32)
        return {"SCORE": feats.sum(axis=-1, keepdims=True)}


def make_ab_ensemble(repository, name: str = "ensemble_ab",
                     legacy: bool = False) -> EnsembleModel:
    """The ``ensemble_dataflow_ab`` bench pair: identical three-step
    graphs over per-arm composing models (suffixed so each arm's
    fusion/execution statistics stay separable), differing ONLY in
    ``device_dataflow``. Outputs are byte-identical across arms —
    the bench's golden-parity gate."""
    suffix = "_legacy" if legacy else ""
    ensemble = EnsembleModel(
        name=name,
        repository=repository,
        steps=[
            ("ab_pre" + suffix, {"RAW": "RAW"}, {"scaled": "SCALED"}),
            ("ab_backbone" + suffix, {"scaled": "SCALED"},
             {"feats": "FEATS"}),
            ("ab_post" + suffix, {"feats": "FEATS"},
             {"SCORE": "SCORE"}),
        ],
        inputs=[TensorSpec("RAW", "FP32", [8])],
        outputs=[TensorSpec("SCORE", "FP32", [1])],
        max_batch_size=32,
    )
    ensemble.device_dataflow = not legacy
    if legacy:
        # Prod-style ensemble-level gather (make_image_ensemble's
        # shape): the strongest legacy arm, not a strawman.
        ensemble.dynamic_batching = True
        ensemble.preferred_batch_sizes = [8, 16, 32]
        ensemble.max_queue_delay_us = 20000
    return ensemble
