"""Llama-style decoder LM: the flagship served model and the
long-context / multi-chip showcase (BASELINE config #5: generate
endpoint with decoupled token streaming).

TPU-first structure:
- bf16 params, matmul-heavy blocks sized for the MXU;
- prefill and decode-step are separate jitted functions; decode keeps
  the KV cache device-resident and updates it via dynamic_update_slice
  (donated, so XLA updates in place);
- sharding comes from client_tpu.parallel rules — heads/ffn/vocab on
  ``tp``, batch on ``dp``, optional ``sp`` for long-context sequence
  parallelism; the same code runs single-chip with a 1x1 mesh.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from collections import deque
from functools import partial
from typing import Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.parallel import LLM_RULES, ShardingRules, create_mesh
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import InferenceServerException


@dataclasses.dataclass
class LlmConfig:
    vocab: int = 259          # 256 bytes + BOS/EOS/PAD
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 704
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


LLAMA3_8B = LlmConfig(
    vocab=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    d_ff=14336, max_seq=8192, rope_theta=500000.0,
)

BOS, EOS, PAD = 256, 257, 258


class ByteTokenizer:
    """Zero-dependency byte-level tokenizer (ids 0-255 = raw bytes)."""

    def encode(self, text: str, bos: bool = True) -> np.ndarray:
        ids = list(text.encode("utf-8"))
        if bos:
            ids = [BOS] + ids
        return np.array(ids, dtype=np.int32)

    def decode(self, ids) -> str:
        data = bytes(int(i) for i in ids if int(i) < 256)
        return data.decode("utf-8", errors="replace")


# -- parameters ------------------------------------------------------------


def init_params(key, cfg: LlmConfig) -> Dict:
    dtype = jnp.dtype(cfg.dtype)
    ks = jax.random.split(key, 4 + cfg.n_layers)
    scale = 0.02

    def norm(k, shape):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(dtype)

    params = {
        "embed": norm(ks[0], (cfg.vocab, cfg.d_model)),
        "unembed": norm(ks[1], (cfg.d_model, cfg.vocab)),
        "final_norm": jnp.ones((cfg.d_model,), dtype=dtype),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[4 + i], 7)
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.d_model,), dtype=dtype),
            "wq": norm(lk[0], (cfg.d_model, cfg.n_heads, cfg.head_dim)),
            "wk": norm(lk[1], (cfg.d_model, cfg.n_kv_heads, cfg.head_dim)),
            "wv": norm(lk[2], (cfg.d_model, cfg.n_kv_heads, cfg.head_dim)),
            "wo": norm(lk[3], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
            "mlp_norm": jnp.ones((cfg.d_model,), dtype=dtype),
            "w_gate": norm(lk[4], (cfg.d_model, cfg.d_ff)),
            "w_up": norm(lk[5], (cfg.d_model, cfg.d_ff)),
            "w_down": norm(lk[6], (cfg.d_ff, cfg.d_model)),
        })
    return params


def param_specs(cfg: LlmConfig, rules: ShardingRules = LLM_RULES) -> Dict:
    """PartitionSpec tree matching init_params (Megatron layout)."""
    layer = {
        "attn_norm": rules.spec("model"),
        "wq": rules.spec("model", "heads", "head_dim"),
        "wk": rules.spec("model", "kv_heads", "head_dim"),
        "wv": rules.spec("model", "kv_heads", "head_dim"),
        "wo": rules.spec("heads", "head_dim", "model"),
        "mlp_norm": rules.spec("model"),
        "w_gate": rules.spec("model", "ffn"),
        "w_up": rules.spec("model", "ffn"),
        "w_down": rules.spec("ffn", "model"),
    }
    return {
        "embed": rules.spec("vocab", "model"),
        "unembed": rules.spec("model", "vocab"),
        "final_norm": rules.spec("model"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


# -- forward ---------------------------------------------------------------


def _rms_norm(x, weight, eps: float = 1e-5):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def _rope(x, positions, theta: float):
    """x: [B, S, H, D]; rotary embedding over the last dim."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    rotated = jnp.stack(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return rotated.reshape(x.shape).astype(x.dtype)


def _attention(q, k, v, mask):
    """q: [B,S,H,D]; k/v: [B,T,Hkv,D] (GQA: H a multiple of Hkv)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    q = q.reshape(b, s, hkv, group, d)
    logits = jnp.einsum("bshgd,bthd->bhgst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d)
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bhgst,bthd->bshgd", probs, v)
    return ctx.reshape(b, s, h, d)


def ring_attention_fn(mesh, axis_name: str = "sp"):
    """Drop-in attention for sequence-sharded full-sequence forwards:
    rotates K/V shards around the ``axis_name`` ring instead of
    letting GSPMD all-gather the full sequence (O(S_local) memory —
    the long-context path). GQA heads are expanded to full heads
    before the ring; the mask argument is ignored because the ring op
    applies global causal masking itself."""
    from client_tpu.parallel.ring_attention import ring_attention

    def attn(q, k, v, mask):  # noqa: ARG001 - causal handled in-op
        h, hkv = q.shape[2], k.shape[2]
        if h != hkv:
            k = jnp.repeat(k, h // hkv, axis=2)
            v = jnp.repeat(v, h // hkv, axis=2)
        return ring_attention(q, k, v, mesh, axis_name=axis_name,
                              causal=True)

    return attn


def _block(layer, x, positions, mask, cfg: LlmConfig, cache=None,
           cache_pos=None, attention_fn=None, cache_pos_vec=None):
    h = _rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    new_cache = None
    if cache is not None:
        ck, cv = cache  # [B, T, Hkv, D]
        if cache_pos_vec is not None:
            # Per-lane write positions (multi-lane decode: each lane
            # is a different sequence at a different length).
            write = jax.vmap(
                lambda c, kv, p: jax.lax.dynamic_update_slice(
                    c, kv, (p, 0, 0)))
            ck = write(ck, k, cache_pos_vec)
            cv = write(cv, v, cache_pos_vec)
        else:
            ck = jax.lax.dynamic_update_slice(ck, k, (0, cache_pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, cache_pos, 0, 0))
        k, v = ck, cv
        new_cache = (ck, cv)
    ctx = (attention_fn or _attention)(q, k, v, mask)
    x = x + jnp.einsum("bshk,hkd->bsd", ctx, layer["wo"])
    h = _rms_norm(x, layer["mlp_norm"])
    gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
    return x + gated @ layer["w_down"], new_cache


def forward(params, tokens, cfg: LlmConfig, attention_fn=None):
    """Full-sequence scoring forward: tokens [B,S] -> logits [B,S,V].
    ``attention_fn`` swaps the attention op (ring_attention_fn for
    sequence-parallel long-context runs)."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))[None]
    for layer in params["layers"]:
        x, _ = _block(layer, x, positions, causal, cfg,
                      attention_fn=attention_fn)
    x = _rms_norm(x, params["final_norm"])
    return (x @ params["unembed"]).astype(jnp.float32)


def init_cache(cfg: LlmConfig, batch: int, dtype=None):
    dtype = dtype or jnp.dtype(cfg.dtype)
    return [
        (
            jnp.zeros((batch, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim),
                      dtype=dtype),
            jnp.zeros((batch, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim),
                      dtype=dtype),
        )
        for _ in range(cfg.n_layers)
    ]


def prefill(params, tokens, cache, cfg: LlmConfig, true_len=None):
    """Process the prompt, fill the cache; returns (logits of the last
    real row, cache). tokens [B,S]; ``true_len`` (traced scalar or
    per-row [B] vector — the batched-join path prefills several
    prompts of different lengths in ONE dispatch) marks the prompt
    length when S is a padded bucket — padded rows write cache slots
    >= true_len, which decode overwrites sequentially before ever
    attending to them, so they never leak into outputs."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    # rows attend to cache slots <= their position
    mask = jnp.tril(
        jnp.ones((s, cfg.max_seq), dtype=bool), k=0
    )[None]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask, cfg,
                            cache=layer_cache, cache_pos=0)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    if true_len is None:
        last = x[:, -1]
    elif jnp.ndim(true_len) >= 1:
        last = jnp.take_along_axis(
            x, (true_len - 1)[:, None, None], axis=1)[:, 0]
    else:
        last = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)[:, 0]
    logits = (last @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


def decode_chunk(params, token, pos, cache, cfg: LlmConfig, length: int):
    """Greedy-decodes ``length`` tokens entirely on device with
    lax.scan: token/pos are traced scalars, the KV cache is the scan
    carry. One host fetch retrieves the whole chunk, so the
    host<->device round-trip cost (exaggerated ~100ms by the axon
    relay on this image, but real on any PCIe/ICI hop) is paid once
    per ``length`` tokens instead of per token. Returns
    (token ids [length], cache)."""

    def step(carry, _):
        tok, p, c = carry
        logits, c = decode_step(params, tok.reshape(1, 1), p, c, cfg)
        nxt = jnp.argmax(logits[0]).astype(jnp.int32)
        return (nxt, p + 1, c), nxt

    (_, _, cache), tokens = jax.lax.scan(
        step, (token.astype(jnp.int32), pos, cache), None, length=length)
    return tokens, cache


def decode_step_multi(params, tokens, pos, cache, cfg: LlmConfig):
    """One step for B independent lanes: tokens [B,1], pos [B] (each
    lane its own position); returns (logits [B,V], cache). Per-lane
    causal masks and cache writes — the kernel under multi-lane
    (continuous-batching-style) serving."""
    positions = pos[:, None]  # [B,1]
    x = params["embed"][tokens]
    mask = (jnp.arange(cfg.max_seq)[None, None, :]
            <= pos[:, None, None])  # [B,1,T]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask, cfg,
                            cache=layer_cache, cache_pos_vec=pos)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    logits = (x[:, -1] @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


def decode_chunk_multi(params, tokens, pos, cache, cfg: LlmConfig,
                       length: int):
    """Greedy-decodes ``length`` tokens for B lanes on device:
    tokens/pos [B]; returns (token ids [length, B], cache). One
    dispatch + one host fetch serves every active lane — requests
    join/leave at chunk boundaries (continuous batching at chunk
    granularity)."""

    def step(carry, _):
        tok, p, c = carry
        logits, c = decode_step_multi(params, tok[:, None], p, c, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B]
        return (nxt, p + 1, c), nxt

    (_, _, cache), toks = jax.lax.scan(
        step, (tokens.astype(jnp.int32), pos.astype(jnp.int32), cache),
        None, length=length)
    return toks, cache


def decode_step(params, token, pos, cache, cfg: LlmConfig):
    """One token step: token [B,1], pos scalar; returns (logits [B,V],
    cache)."""
    b = token.shape[0]
    x = params["embed"][token]
    positions = jnp.full((b, 1), pos, dtype=jnp.int32)
    mask = (jnp.arange(cfg.max_seq) <= pos)[None, None]  # [1,1,T]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask[0], cfg,
                            cache=layer_cache, cache_pos=pos)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    logits = (x[:, -1] @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


def loss_fn(params, tokens, targets, cfg: LlmConfig, attention_fn=None):
    logits = forward(params, tokens, cfg, attention_fn=attention_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    mask = (targets != PAD).astype(jnp.float32)
    return jnp.sum(nll[..., 0] * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def train_step(params, tokens, targets, cfg: LlmConfig, lr: float = 1e-3,
               attention_fn=None):
    """SGD training step (forward + backward + update) — the function
    the multi-chip dryrun jits over the mesh. ``attention_fn`` selects
    the attention op (ring attention for context-parallel runs)."""
    loss, grads = jax.value_and_grad(
        partial(loss_fn, cfg=cfg, attention_fn=attention_fn))(
        params, tokens, targets
    )
    new_params = jax.tree.map(
        lambda w, g: (w - lr * g.astype(w.dtype)).astype(w.dtype),
        params, grads,
    )
    return new_params, loss


# -- served model ----------------------------------------------------------


class _GenRequest:
    """One in-flight generation riding a decode lane."""

    def __init__(self, prompt, max_tokens: int, ignore_eos: bool):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.ignore_eos = ignore_eos
        self.delivered = 0
        self.queue: queue.Queue = queue.Queue()
        self.error: Optional[str] = None
        # Set when the consumer abandons the stream (client
        # disconnect): the scheduler frees the lane at the next chunk
        # boundary instead of decoding the full budget into nowhere.
        self.cancelled = False

    def finish(self):
        self.queue.put(None)

    def fail(self, message: str):
        self.error = message
        self.queue.put(None)


class LlmModel(ServedModel):
    """Decoupled generate endpoint: text in, token stream out.

    Inputs: text_input BYTES [1]; max_tokens INT32 [1] (optional);
    outputs: text_output BYTES [1] per streamed response. Greedy
    decoding with multi-lane batched decode: a scheduler thread steps
    ``decode_lanes`` independent sequences through one jitted
    decode_chunk_multi dispatch, so concurrent requests share device
    work instead of serializing (continuous batching at chunk
    granularity — requests join/leave at chunk boundaries). Joins
    prefill in one batched dispatch per padded bucket and their caches
    are row-inserted into the batched KV cache, which never leaves the
    device.

    The decode pipeline is split into a dispatch side (scheduler
    thread: prefills + decode chunks launched back-to-back, last
    tokens carried ON DEVICE between chunks) and a delivery side
    (delivery thread: waits on each chunk's pooled device->host fetch
    in dispatch order and routes tokens to requests). Up to
    MAX_INFLIGHT chunks are in flight, so the host-fetch round trip
    (~65 ms through this image's relay, real on any PCIe/ICI hop)
    overlaps decode compute instead of stalling the token stream every
    STREAM_CHUNK tokens — inter-token latency at a chunk boundary is
    the chunk's compute time, not the fetch latency.
    """

    decoupled = True
    platform = "jax"
    # Tokens per device-side decode dispatch (and per host fetch).
    STREAM_CHUNK = 8
    # Decode chunks allowed in flight (dispatched, fetch pending).
    # Pipelining bound: the relay's ~65 ms fetch overlaps roughly
    # fetch_latency / chunk_compute (~4) chunks; beyond that it is
    # run-ahead waste on finished requests and queue-drain latency
    # ahead of every join's first token.
    MAX_INFLIGHT = 5

    def __init__(self, name: str = "llm", cfg: Optional[LlmConfig] = None,
                 mesh=None, rules: ShardingRules = LLM_RULES,
                 seed: int = 0, decode_lanes: int = 4):
        super().__init__()
        self.name = name
        self.cfg = cfg or LlmConfig()
        self._tokenizer = ByteTokenizer()
        self.inputs = [
            TensorSpec("text_input", "BYTES", [1]),
            TensorSpec("max_tokens", "INT32", [1], optional=True),
            TensorSpec("ignore_eos", "BOOL", [1], optional=True),
        ]
        self.outputs = [TensorSpec("text_output", "BYTES", [1])]

        key = jax.random.PRNGKey(seed)
        params = init_params(key, self.cfg)
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding

            specs = param_specs(self.cfg, rules)
            params = jax.tree.map(
                lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
                params, specs,
                is_leaf=lambda x: isinstance(x, jnp.ndarray),
            )
        self._params = params
        cfg_static = self.cfg

        def _prefill_first(p, t, c, n):
            # argmax folded in: the scheduler only needs the first
            # TOKEN, and a separate jitted argmax would compile per
            # batch shape mid-serving.
            logits, new_cache = prefill(p, t, c, cfg_static, true_len=n)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), new_cache

        self._prefill = jax.jit(_prefill_first)
        self._decode_chunk_multi = jax.jit(
            lambda p, tok, pos, c: decode_chunk_multi(
                p, tok, pos, c, cfg_static, self.STREAM_CHUNK),
            donate_argnums=(3,),
        )
        # Inserts row `b` of a batched prefill cache into lane `i` of
        # the decode cache (b and i are traced: one compile serves
        # every (row, lane) pair).
        self._lane_insert_row = jax.jit(
            lambda batched, multi, b, i: jax.tree.map(
                lambda dst, src: jax.lax.dynamic_update_slice(
                    dst, jax.lax.dynamic_slice_in_dim(src, b, 1, axis=0),
                    (i, 0, 0, 0)),
                batched, multi),
            donate_argnums=(0,),
        )
        # Scatter first tokens of joining lanes into the device-side
        # last-token vector the next decode chunk consumes.
        self._set_lane_tokens = jax.jit(
            lambda toks, idx, vals: toks.at[idx].set(vals),
            donate_argnums=(0,),
        )

        # Prefill executables keyed by (batch, bucket). Batched-join
        # prefill shapes are compiled AHEAD in a background thread the
        # first time a new shape shows up — an inline compile (seconds)
        # would stall every active token stream; until the compile
        # lands, joins fall back to the already-compiled batch-1 path.
        self._prefill_exec: Dict[tuple, object] = {}
        self._prefill_compiling: set = set()
        self._prefill_exec_lock = threading.Lock()

        self._lanes = max(1, int(decode_lanes))
        self._sched_lock = threading.Lock()
        self._sched_cv = threading.Condition(self._sched_lock)
        self._sched_thread: Optional[threading.Thread] = None
        self._delivery_thread: Optional[threading.Thread] = None
        self._fetch_pool = None
        self._sched_stop = False
        self._gen = 0  # bumped on crash: stale threads exit
        self._join_queue: list = []
        self._active: Dict[int, _GenRequest] = {}
        self._free_lanes = list(range(self._lanes))
        self._lane_pos = [0] * self._lanes  # host bookkeeping
        self._tokens_dev = None  # [lanes] int32 device carry
        self._batched_cache = None
        self._delivery_queue: deque = deque()
        self._inflight = 0  # dispatched-not-yet-delivered decode chunks

    # -- scheduler -------------------------------------------------------

    def _ensure_scheduler(self):
        with self._sched_cv:
            if self._sched_stop:
                return
            if self._fetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                # Sized so every in-flight chunk's device->host fetch
                # overlaps (the relay pipelines concurrent fetches:
                # 8 concurrent transfers complete in one ~65 ms round
                # trip, measured on this image).
                self._fetch_pool = ThreadPoolExecutor(
                    max_workers=self.MAX_INFLIGHT + 2,
                    thread_name_prefix="llm-fetch-%s" % self.name)
            if self._sched_thread is None:
                self._sched_thread = threading.Thread(
                    target=self._scheduler_loop, args=(self._gen,),
                    daemon=True, name="llm-decode-%s" % self.name)
                self._sched_thread.start()
            if self._delivery_thread is None:
                self._delivery_thread = threading.Thread(
                    target=self._delivery_loop, args=(self._gen,),
                    daemon=True, name="llm-deliver-%s" % self.name)
                self._delivery_thread.start()

    def _deliver(self, lane: int, req: _GenRequest, token: int) -> bool:
        """Pushes one token; returns False when the request finished
        (EOS, budget, or consumer abandonment). Caller holds
        _sched_cv."""
        if req.cancelled:
            req.finish()
            return False
        if token == EOS and not req.ignore_eos:
            req.finish()
            return False
        req.queue.put(int(token))
        req.delivered += 1
        if req.delivered >= req.max_tokens:
            req.finish()
            return False
        return True

    def _release_lane(self, lane: int):
        """Caller holds _sched_cv."""
        self._active.pop(lane, None)
        self._lane_pos[lane] = 0
        self._free_lanes.append(lane)

    def _compile_prefill(self, b: int, bucket: int):
        """AOT-compiles the (b, bucket) prefill and publishes it in
        _prefill_exec. Runs inline for batch 1 (first use of a new
        bucket has nothing to fall back to) and on a background thread
        for batched shapes."""
        toks = jax.ShapeDtypeStruct((b, bucket), jnp.int32)
        lens = jax.ShapeDtypeStruct((b,), jnp.int32)
        cache = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            init_cache(self.cfg, b))
        compiled = self._prefill.lower(
            self._params, toks, cache, lens).compile()
        with self._prefill_exec_lock:
            self._prefill_exec[(b, bucket)] = compiled
            self._prefill_compiling.discard((b, bucket))

    def _get_prefill_exec(self, b: int, bucket: int):
        """Returns the compiled (b, bucket) prefill, or None while a
        background compile is still in flight (caller falls back to
        batch 1). Batch 1 always blocks until compiled."""
        key = (b, bucket)
        with self._prefill_exec_lock:
            compiled = self._prefill_exec.get(key)
            if compiled is not None:
                return compiled
            if b > 1 and key in self._prefill_compiling:
                return None
            if b > 1:
                self._prefill_compiling.add(key)
        if b == 1:
            self._compile_prefill(1, bucket)
            return self._prefill_exec[key]
        threading.Thread(
            target=self._compile_prefill_safely, args=(b, bucket),
            daemon=True, name="llm-prefill-compile").start()
        return None

    def _compile_prefill_safely(self, b: int, bucket: int):
        try:
            self._compile_prefill(b, bucket)
        except Exception:  # noqa: BLE001 — joins keep falling back
            with self._prefill_exec_lock:
                self._prefill_compiling.discard((b, bucket))

    def _dispatch_joins(self, joins, gen: int):
        """Batched prefill for a set of (lane, request) joins: prompts
        sharing a padded bucket go through ONE prefill dispatch (batch
        padded to a power of two so XLA compiles per (B, bucket), not
        per request mix), their caches are row-inserted into the
        decode cache, and the first tokens are scattered into the
        device token vector. Nothing here blocks on the device — the
        first tokens travel to clients through the delivery queue like
        any decode chunk. Runs on the scheduler thread, no lock held
        during device work."""
        groups: Dict[int, list] = {}
        for lane, req in joins:
            n = len(req.prompt)
            bucket = 16
            while bucket < n:
                bucket *= 2
            bucket = min(bucket, self.cfg.max_seq)
            groups.setdefault(bucket, []).append((lane, req))
        batches = []
        for bucket, group in groups.items():
            b = 1
            while b < len(group):
                b *= 2
            compiled = self._get_prefill_exec(b, bucket)
            if compiled is None:
                # Batched shape still compiling in the background:
                # fall back to batch-1 prefills rather than stalling
                # every active stream for seconds.
                one = self._get_prefill_exec(1, bucket)
                batches.extend((bucket, 1, one, [entry]) for entry in group)
            else:
                batches.append((bucket, b, compiled, group))
        for batch_idx, (bucket, b, compiled, group) in enumerate(batches):
            padded = np.full((b, bucket), PAD, dtype=np.int32)
            lens = np.ones((b,), dtype=np.int32)
            for row, (lane, req) in enumerate(group):
                padded[row, :len(req.prompt)] = req.prompt
                lens[row] = len(req.prompt)
            firsts, multi_cache = compiled(
                self._params, jnp.asarray(padded),
                init_cache(self.cfg, b), jnp.asarray(lens))  # [b] device
            lanes_idx = np.array([lane for lane, _ in group],
                                 dtype=np.int32)
            # Row-insert into locals; publish under the lock only after
            # the gen check below — a concurrent _crash rebuilds the
            # cache/token carry and an unlocked old-generation rebind
            # here would clobber the new generation's fresh state.
            with self._sched_cv:
                cache = self._batched_cache
                tokens_dev = self._tokens_dev
            for row, (lane, req) in enumerate(group):
                cache = self._lane_insert_row(
                    cache, multi_cache, np.int32(row), np.int32(lane))
            tokens_dev = self._set_lane_tokens(
                tokens_dev, jnp.asarray(lanes_idx), firsts[:len(group)])
            fut = self._fetch_pool.submit(np.asarray, firsts)
            with self._sched_cv:
                if self._sched_stop or self._gen != gen:
                    # Unload or a concurrent _crash reset the pipeline.
                    # Fail the current group AND every not-yet-run
                    # group — they are all popped off _join_queue and
                    # invisible to any other cleanup path. After a
                    # crash the lane list was already rebuilt, so only
                    # re-add lanes while this generation is live.
                    for _, _, _, late_group in batches[batch_idx:]:
                        for lane, req in late_group:
                            req.fail("model unloaded")
                            if self._gen == gen:
                                self._free_lanes.append(lane)
                    return
                self._batched_cache = cache
                self._tokens_dev = tokens_dev
                for row, (lane, req) in enumerate(group):
                    self._lane_pos[lane] = len(req.prompt)
                    self._active[lane] = req
                self._delivery_queue.append(("join", fut, list(group)))
                self._sched_cv.notify_all()

    def _scheduler_loop(self, gen: int):
        """Dispatch side of the decode pipeline: prefills joins and
        launches decode chunks back-to-back WITHOUT waiting for their
        device->host fetches — each chunk's token fetch rides the
        fetch pool and reaches clients through _delivery_loop. The
        relay's ~65 ms fetch latency then overlaps the next chunks'
        compute instead of gating the token cadence (inter-chunk gap =
        chunk compute time, not fetch latency)."""
        try:
            while True:
                joins = []
                with self._sched_cv:
                    while (not self._sched_stop and self._gen == gen
                           and not (self._join_queue and self._free_lanes)
                           and not (self._active
                                    and self._inflight < self.MAX_INFLIGHT)):
                        self._sched_cv.wait()
                    if self._sched_stop or self._gen != gen:
                        return
                    while self._join_queue and self._free_lanes:
                        req = self._join_queue.pop(0)
                        if req.cancelled:  # abandoned while queued
                            req.finish()
                            continue
                        joins.append((self._free_lanes.pop(0), req))
                if joins:
                    try:
                        self._dispatch_joins(joins, gen)
                    except Exception as e:  # noqa: BLE001
                        # Popped requests are in neither _active nor
                        # _join_queue, so the crash handler cannot see
                        # all of them — fail them here or their clients
                        # block forever on queue.get().
                        with self._sched_cv:
                            for lane2, req2 in joins:
                                if self._active.get(lane2) is not req2:
                                    req2.fail("llm prefill failed: %s" % e)
                                    if (self._gen == gen
                                            and lane2 not in self._active):
                                        self._free_lanes.append(lane2)
                        raise
                    continue  # more joins may fit before the next chunk
                with self._sched_cv:
                    if (not self._active or self._batched_cache is None
                            or self._inflight >= self.MAX_INFLIGHT):
                        continue
                    pos_host = np.asarray(self._lane_pos, dtype=np.int32)
                    params = self._params
                    tokens_dev = self._tokens_dev
                    cache = self._batched_cache
                toks, new_cache = self._decode_chunk_multi(
                    params, tokens_dev, jnp.asarray(pos_host), cache)
                fut = self._fetch_pool.submit(np.asarray, toks)
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        # A concurrent _crash/unload reset the pipeline
                        # while this dispatch ran unlocked — registering
                        # the record would hand the NEW generation a
                        # stale (possibly failing) future, re-mark
                        # rebuilt free lanes active, or clobber the new
                        # generation's freshly rebuilt cache/token carry
                        # with this old generation's outputs.
                        return
                    self._batched_cache = new_cache
                    self._tokens_dev = toks[-1]  # [lanes] device carry
                    snapshot = dict(self._active)
                    for lane in snapshot:
                        self._lane_pos[lane] += self.STREAM_CHUNK
                    self._inflight += 1
                    self._delivery_queue.append(("chunk", fut, snapshot))
                    self._sched_cv.notify_all()
        except Exception as e:  # noqa: BLE001 — fail all riders loudly
            self._crash("llm scheduler failed: %s" % e, gen)

    def _delivery_loop(self, gen: int):
        """Consumer side of the decode pipeline: waits on each fetched
        token block IN DISPATCH ORDER and routes tokens to their
        requests. Runs concurrently with the scheduler's next
        dispatches, so the fetch latency is pipelined away."""
        try:
            while True:
                with self._sched_cv:
                    while (not self._sched_stop and self._gen == gen
                           and not self._delivery_queue):
                        self._sched_cv.wait()
                    if self._sched_stop or self._gen != gen:
                        return
                    kind, fut, payload = self._delivery_queue.popleft()
                ids = fut.result()  # blocks ~one relay round trip
                if kind == "join":
                    with self._sched_cv:
                        if self._gen != gen:
                            return
                        for row, (lane, req) in enumerate(payload):
                            if self._active.get(lane) is not req:
                                continue  # finished/cancelled already
                            if not self._deliver(lane, req, int(ids[row])):
                                self._release_lane(lane)
                        self._sched_cv.notify_all()
                    continue
                with self._sched_cv:
                    if self._gen != gen:
                        return
                    for lane, req in payload.items():
                        if self._active.get(lane) is not req:
                            continue  # lane re-assigned since dispatch
                        alive = True
                        for token in ids[:, lane]:
                            alive = self._deliver(lane, req, int(token))
                            if not alive:
                                break
                        if alive and (len(req.prompt) + req.delivered
                                      >= self.cfg.max_seq - 1):
                            req.finish()
                            alive = False
                        if not alive:
                            self._release_lane(lane)
                    self._inflight -= 1
                    self._sched_cv.notify_all()
        except Exception as e:  # noqa: BLE001
            self._crash("llm delivery failed: %s" % e, gen)

    def _collect_riders(self):
        """Every request the pipeline still owes tokens to: active
        lanes, queued joins, and requests riding undelivered records.
        Caller holds _sched_cv."""
        riders = list(self._active.values()) + self._join_queue
        for _, _, payload in self._delivery_queue:
            if isinstance(payload, dict):
                riders.extend(payload.values())
            else:
                riders.extend(req for _, req in payload)
        return riders

    def _crash(self, message: str, gen: int):
        """Fails every rider and resets the pipeline so a later
        request restarts it cleanly (the donated cache may already be
        consumed; leaked lanes would leave a restart spinning)."""
        with self._sched_cv:
            if self._gen != gen:  # another thread already reset
                return
            self._gen += 1
            for req in self._collect_riders():
                req.fail(message)
            self._active.clear()
            self._join_queue.clear()
            self._delivery_queue.clear()
            self._inflight = 0
            self._free_lanes = list(range(self._lanes))
            self._lane_pos = [0] * self._lanes
            self._tokens_dev = None
            self._batched_cache = None
            self._sched_thread = None
            self._delivery_thread = None
            self._sched_cv.notify_all()

    def unload(self) -> None:
        with self._sched_cv:
            self._sched_stop = True
            for req in self._collect_riders():
                req.fail("model unloaded")
            self._active.clear()
            self._join_queue.clear()
            self._delivery_queue.clear()
            self._inflight = 0
            self._sched_cv.notify_all()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=10)
        if self._delivery_thread is not None:
            self._delivery_thread.join(timeout=10)
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)

    def _generate(self, inputs, parameters):
        text = inputs["text_input"].reshape(-1)[0]
        if isinstance(text, bytes):
            text = text.decode("utf-8", errors="replace")
        else:
            text = str(text)
        max_tokens = int(
            inputs.get("max_tokens", np.array([32])).reshape(-1)[0]
        )
        max_tokens = max(1, min(max_tokens, self.cfg.max_seq - 2))
        ignore_eos = bool(
            inputs.get("ignore_eos", np.array([False])).reshape(-1)[0]
        )
        prompt = self._tokenizer.encode(text)
        prompt = prompt[-(self.cfg.max_seq - max_tokens - 1):]
        request = _GenRequest(prompt, max_tokens, ignore_eos)
        with self._sched_cv:
            if self._sched_stop:
                raise InferenceServerException(
                    "model '%s' is unloaded" % self.name,
                    status="UNAVAILABLE")
            if self._batched_cache is None:
                self._batched_cache = init_cache(self.cfg, self._lanes)
            if self._tokens_dev is None:
                self._tokens_dev = jnp.full(
                    (self._lanes,), PAD, dtype=jnp.int32)
            self._join_queue.append(request)
            self._sched_cv.notify_all()
        # AFTER enqueuing: a scheduler that crashed between the
        # liveness check and the append would otherwise leave the
        # request stranded — this restart sees it in the queue.
        self._ensure_scheduler()
        try:
            while True:
                token = request.queue.get()
                if token is None:
                    break
                yield token
        finally:
            # Consumer gone (client disconnect closes the generator):
            # let the scheduler reclaim the lane at the next chunk.
            request.cancelled = True
        if request.error is not None:
            raise InferenceServerException(request.error,
                                           status="INTERNAL")

    def infer_stream(self, inputs, parameters=None
                     ) -> Iterator[Dict[str, np.ndarray]]:
        for token in self._generate(inputs, parameters or {}):
            piece = self._tokenizer.decode([token])
            yield {
                "text_output": np.array([piece.encode()], dtype=np.object_)
            }

    def infer(self, inputs, parameters=None) -> Dict[str, np.ndarray]:
        tokens = list(self._generate(inputs, parameters or {}))
        text = self._tokenizer.decode(tokens)
        return {"text_output": np.array([text.encode()], dtype=np.object_)}

    def flops_per_token(self) -> float:
        """Decode FLOPs per generated token ≈ 2 * parameter count
        (matmul-dominated; KV-cache attention reads are minor at tiny
        sequence lengths) — the serving-MFU numerator."""
        import jax as _jax

        n_params = sum(int(x.size) for x in _jax.tree_util.tree_leaves(
            self._params))
        return 2.0 * n_params

    def warmup(self) -> None:
        # Prime the prefill shapes concurrent serving hits (power-of
        # -two join batches x the two common prompt buckets) so no
        # multi-second XLA compile lands mid-stream; the persistent
        # compilation cache makes repeat warmups near-free.
        pow2s = [1]
        while pow2s[-1] < self._lanes:  # ceiling pow2 covers any group
            pow2s.append(pow2s[-1] * 2)
        for b in pow2s:
            for bucket in sorted({min(16, self.cfg.max_seq),
                                  min(64, self.cfg.max_seq)}):
                if (b, bucket) not in self._prefill_exec:
                    try:
                        self._compile_prefill(b, bucket)
                    except Exception:  # noqa: BLE001 — warmup best-effort
                        pass
        # The join path's small shape-dependent kernels (cache row
        # insert per prefill batch, token scatter per join-group size)
        # also compile per shape — prime them too, or the first
        # concurrent join round stalls every stream for the compile.
        try:
            for b in pow2s:
                scratch = self._lane_insert_row(
                    init_cache(self.cfg, self._lanes),
                    init_cache(self.cfg, b), np.int32(0), np.int32(0))
                del scratch
            toks = jnp.full((self._lanes,), PAD, dtype=jnp.int32)
            for g in range(1, self._lanes + 1):
                toks = self._set_lane_tokens(
                    toks, jnp.arange(g, dtype=jnp.int32),
                    jnp.full((g,), PAD, dtype=jnp.int32))
            del toks
        except Exception:  # noqa: BLE001 — warmup best-effort
            pass
        list(self.infer_stream({
            "text_input": np.array([b"hi"], dtype=np.object_),
            "max_tokens": np.array([2], dtype=np.int32),
        }))
