"""Llama-style decoder LM: the flagship served model and the
long-context / multi-chip showcase (BASELINE config #5: generate
endpoint with decoupled token streaming).

TPU-first structure:
- bf16 params, matmul-heavy blocks sized for the MXU;
- prefill and decode-step are separate jitted functions; decode keeps
  the KV cache device-resident and updates it via dynamic_update_slice
  (donated, so XLA updates in place);
- sharding comes from client_tpu.parallel rules — heads/ffn/vocab on
  ``tp``, batch on ``dp``, optional ``sp`` for long-context sequence
  parallelism; the same code runs single-chip with a 1x1 mesh.
"""

from __future__ import annotations

import dataclasses
import hashlib
import queue
import threading
import time
from collections import OrderedDict, deque
from functools import partial
from typing import Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.parallel import LLM_RULES, ShardingRules, create_mesh
from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.status_map import retryable_error
from client_tpu.utils import InferenceServerException


@dataclasses.dataclass
class LlmConfig:
    vocab: int = 259          # 256 bytes + BOS/EOS/PAD
    d_model: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 704
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


LLAMA3_8B = LlmConfig(
    vocab=128256, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    d_ff=14336, max_seq=8192, rope_theta=500000.0,
)

BOS, EOS, PAD = 256, 257, 258


class ByteTokenizer:
    """Zero-dependency byte-level tokenizer (ids 0-255 = raw bytes)."""

    def encode(self, text: str, bos: bool = True) -> np.ndarray:
        ids = list(text.encode("utf-8"))
        if bos:
            ids = [BOS] + ids
        return np.array(ids, dtype=np.int32)

    def decode(self, ids) -> str:
        data = bytes(int(i) for i in ids if int(i) < 256)
        return data.decode("utf-8", errors="replace")


# -- parameters ------------------------------------------------------------


def init_params(key, cfg: LlmConfig) -> Dict:
    dtype = jnp.dtype(cfg.dtype)
    ks = jax.random.split(key, 4 + cfg.n_layers)
    scale = 0.02

    def norm(k, shape):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(dtype)

    params = {
        "embed": norm(ks[0], (cfg.vocab, cfg.d_model)),
        "unembed": norm(ks[1], (cfg.d_model, cfg.vocab)),
        "final_norm": jnp.ones((cfg.d_model,), dtype=dtype),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[4 + i], 7)
        params["layers"].append({
            "attn_norm": jnp.ones((cfg.d_model,), dtype=dtype),
            "wq": norm(lk[0], (cfg.d_model, cfg.n_heads, cfg.head_dim)),
            "wk": norm(lk[1], (cfg.d_model, cfg.n_kv_heads, cfg.head_dim)),
            "wv": norm(lk[2], (cfg.d_model, cfg.n_kv_heads, cfg.head_dim)),
            "wo": norm(lk[3], (cfg.n_heads, cfg.head_dim, cfg.d_model)),
            "mlp_norm": jnp.ones((cfg.d_model,), dtype=dtype),
            "w_gate": norm(lk[4], (cfg.d_model, cfg.d_ff)),
            "w_up": norm(lk[5], (cfg.d_model, cfg.d_ff)),
            "w_down": norm(lk[6], (cfg.d_ff, cfg.d_model)),
        })
    return params


def param_specs(cfg: LlmConfig, rules: ShardingRules = LLM_RULES) -> Dict:
    """PartitionSpec tree matching init_params (Megatron layout)."""
    layer = {
        "attn_norm": rules.spec("model"),
        "wq": rules.spec("model", "heads", "head_dim"),
        "wk": rules.spec("model", "kv_heads", "head_dim"),
        "wv": rules.spec("model", "kv_heads", "head_dim"),
        "wo": rules.spec("heads", "head_dim", "model"),
        "mlp_norm": rules.spec("model"),
        "w_gate": rules.spec("model", "ffn"),
        "w_up": rules.spec("model", "ffn"),
        "w_down": rules.spec("ffn", "model"),
    }
    return {
        "embed": rules.spec("vocab", "model"),
        "unembed": rules.spec("model", "vocab"),
        "final_norm": rules.spec("model"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


# -- forward ---------------------------------------------------------------


def _rms_norm(x, weight, eps: float = 1e-5):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight


def _rope(x, positions, theta: float):
    """x: [B, S, H, D]; rotary embedding over the last dim."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    rotated = jnp.stack(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return rotated.reshape(x.shape).astype(x.dtype)


def _attention(q, k, v, mask):
    """q: [B,S,H,D]; k/v: [B,T,Hkv,D] (GQA: H a multiple of Hkv)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    q = q.reshape(b, s, hkv, group, d)
    logits = jnp.einsum("bshgd,bthd->bhgst", q, k).astype(jnp.float32)
    logits = logits / np.sqrt(d)
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bhgst,bthd->bshgd", probs, v)
    return ctx.reshape(b, s, h, d)


def ring_attention_fn(mesh, axis_name: str = "sp"):
    """Drop-in attention for sequence-sharded full-sequence forwards:
    rotates K/V shards around the ``axis_name`` ring instead of
    letting GSPMD all-gather the full sequence (O(S_local) memory —
    the long-context path). GQA heads are expanded to full heads
    before the ring; the mask argument is ignored because the ring op
    applies global causal masking itself."""
    from client_tpu.parallel.ring_attention import ring_attention

    def attn(q, k, v, mask):  # noqa: ARG001 - causal handled in-op
        h, hkv = q.shape[2], k.shape[2]
        if h != hkv:
            k = jnp.repeat(k, h // hkv, axis=2)
            v = jnp.repeat(v, h // hkv, axis=2)
        return ring_attention(q, k, v, mesh, axis_name=axis_name,
                              causal=True)

    return attn


def _block(layer, x, positions, mask, cfg: LlmConfig, cache=None,
           cache_pos=None, attention_fn=None, cache_pos_vec=None):
    h = _rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    new_cache = None
    if cache is not None:
        ck, cv = cache  # [B, T, Hkv, D]
        if cache_pos_vec is not None:
            # Per-lane write positions (multi-lane decode: each lane
            # is a different sequence at a different length).
            write = jax.vmap(
                lambda c, kv, p: jax.lax.dynamic_update_slice(
                    c, kv, (p, 0, 0)))
            ck = write(ck, k, cache_pos_vec)
            cv = write(cv, v, cache_pos_vec)
        else:
            ck = jax.lax.dynamic_update_slice(ck, k, (0, cache_pos, 0, 0))
            cv = jax.lax.dynamic_update_slice(cv, v, (0, cache_pos, 0, 0))
        k, v = ck, cv
        new_cache = (ck, cv)
    ctx = (attention_fn or _attention)(q, k, v, mask)
    x = x + jnp.einsum("bshk,hkd->bsd", ctx, layer["wo"])
    h = _rms_norm(x, layer["mlp_norm"])
    gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
    return x + gated @ layer["w_down"], new_cache


def forward(params, tokens, cfg: LlmConfig, attention_fn=None):
    """Full-sequence scoring forward: tokens [B,S] -> logits [B,S,V].
    ``attention_fn`` swaps the attention op (ring_attention_fn for
    sequence-parallel long-context runs)."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))[None]
    for layer in params["layers"]:
        x, _ = _block(layer, x, positions, causal, cfg,
                      attention_fn=attention_fn)
    x = _rms_norm(x, params["final_norm"])
    return (x @ params["unembed"]).astype(jnp.float32)


def init_cache(cfg: LlmConfig, batch: int, dtype=None, length=None):
    """Dense per-lane KV cache. ``length`` (default ``max_seq``) sizes
    the sequence axis — the paged path prefills into a bucket-sized
    scratch cache instead of a full ``max_seq`` reservation."""
    dtype = dtype or jnp.dtype(cfg.dtype)
    length = length or cfg.max_seq
    return [
        (
            jnp.zeros((batch, length, cfg.n_kv_heads, cfg.head_dim),
                      dtype=dtype),
            jnp.zeros((batch, length, cfg.n_kv_heads, cfg.head_dim),
                      dtype=dtype),
        )
        for _ in range(cfg.n_layers)
    ]


def prefill(params, tokens, cache, cfg: LlmConfig, true_len=None):
    """Process the prompt, fill the cache; returns (logits of the last
    real row, cache). tokens [B,S]; ``true_len`` (traced scalar or
    per-row [B] vector — the batched-join path prefills several
    prompts of different lengths in ONE dispatch) marks the prompt
    length when S is a padded bucket — padded rows write cache slots
    >= true_len, which decode overwrites sequentially before ever
    attending to them, so they never leak into outputs."""
    b, s = tokens.shape
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    # rows attend to cache slots <= their position; mask width follows
    # the cache's sequence axis (max_seq for the dense arm, the padded
    # prompt bucket for the paged arm's scratch prefill).
    mask = jnp.tril(
        jnp.ones((s, cache[0][0].shape[1]), dtype=bool), k=0
    )[None]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask, cfg,
                            cache=layer_cache, cache_pos=0)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    if true_len is None:
        last = x[:, -1]
    elif jnp.ndim(true_len) >= 1:
        last = jnp.take_along_axis(
            x, (true_len - 1)[:, None, None], axis=1)[:, 0]
    else:
        last = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)[:, 0]
    logits = (last @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


def decode_chunk(params, token, pos, cache, cfg: LlmConfig, length: int):
    """Greedy-decodes ``length`` tokens entirely on device with
    lax.scan: token/pos are traced scalars, the KV cache is the scan
    carry. One host fetch retrieves the whole chunk, so the
    host<->device round-trip cost (exaggerated ~100ms by the axon
    relay on this image, but real on any PCIe/ICI hop) is paid once
    per ``length`` tokens instead of per token. Returns
    (token ids [length], cache)."""

    def step(carry, _):
        tok, p, c = carry
        logits, c = decode_step(params, tok.reshape(1, 1), p, c, cfg)
        nxt = jnp.argmax(logits[0]).astype(jnp.int32)
        return (nxt, p + 1, c), nxt

    (_, _, cache), tokens = jax.lax.scan(
        step, (token.astype(jnp.int32), pos, cache), None, length=length)
    return tokens, cache


def decode_step_multi(params, tokens, pos, cache, cfg: LlmConfig):
    """One step for B independent lanes: tokens [B,1], pos [B] (each
    lane its own position); returns (logits [B,V], cache). Per-lane
    causal masks and cache writes — the kernel under multi-lane
    (continuous-batching-style) serving."""
    positions = pos[:, None]  # [B,1]
    x = params["embed"][tokens]
    mask = (jnp.arange(cfg.max_seq)[None, None, :]
            <= pos[:, None, None])  # [B,1,T]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask, cfg,
                            cache=layer_cache, cache_pos_vec=pos)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    logits = (x[:, -1] @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


def decode_chunk_multi(params, tokens, pos, cache, cfg: LlmConfig,
                       length: int):
    """Greedy-decodes ``length`` tokens for B lanes on device:
    tokens/pos [B]; returns (token ids [length, B], cache). One
    dispatch + one host fetch serves every active lane — requests
    join/leave at chunk boundaries (continuous batching at chunk
    granularity)."""

    def step(carry, _):
        tok, p, c = carry
        logits, c = decode_step_multi(params, tok[:, None], p, c, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B]
        return (nxt, p + 1, c), nxt

    (_, _, cache), toks = jax.lax.scan(
        step, (tokens.astype(jnp.int32), pos.astype(jnp.int32), cache),
        None, length=length)
    return toks, cache


def decode_step(params, token, pos, cache, cfg: LlmConfig):
    """One token step: token [B,1], pos scalar; returns (logits [B,V],
    cache)."""
    b = token.shape[0]
    x = params["embed"][token]
    positions = jnp.full((b, 1), pos, dtype=jnp.int32)
    mask = (jnp.arange(cfg.max_seq) <= pos)[None, None]  # [1,1,T]
    new_cache = []
    for layer, layer_cache in zip(params["layers"], cache):
        x, updated = _block(layer, x, positions, mask[0], cfg,
                            cache=layer_cache, cache_pos=pos)
        new_cache.append(updated)
    x = _rms_norm(x, params["final_norm"])
    logits = (x[:, -1] @ params["unembed"]).astype(jnp.float32)
    return logits, new_cache


# -- paged KV cache --------------------------------------------------------
#
# vLLM-style layout: one device-resident page pool per layer
# (``[num_pages, page_size, n_kv_heads, head_dim]`` for K and V) plus a
# per-lane block table of page ids. A lane touches only the pages its
# sequence actually occupies, so HBM (and attention width — the tables
# are bucketed to the longest live sequence) scales with live tokens,
# not ``lanes x max_seq``. Kernels address the pool through a flattened
# ``[num_pages * page_size, ...]`` view; ``num_pages * page_size`` is
# the out-of-bounds sentinel slot — scatters to it are dropped
# (``mode="drop"``), which is how padded rows, finished lanes, and
# shared (copy-on-write) pages are write-protected.


def page_pool_axis(mesh):
    """The mesh axis the PAGE dimension shards over: ``tp`` when
    present (the slice's tensor axis — pages then live alongside the
    head shards that read them), else the largest nontrivial axis;
    None for a trivial/absent mesh (unsharded pool)."""
    if mesh is None:
        return None
    sizes = dict(mesh.shape)
    if sizes.get("tp", 1) > 1:
        return "tp"
    axis = max(sizes, key=lambda a: sizes[a]) if sizes else None
    return axis if axis is not None and sizes[axis] > 1 else None


def page_axis_shards(mesh) -> int:
    """How many ways the page axis splits over ``mesh`` (1 = dense
    single-device pool). num_pages must be a multiple of this."""
    axis = page_pool_axis(mesh)
    return int(mesh.shape[axis]) if axis is not None else 1


def init_page_pool(cfg: LlmConfig, num_pages: int, page_size: int,
                   dtype=None, mesh=None):
    dtype = dtype or jnp.dtype(cfg.dtype)
    pools = [
        (
            jnp.zeros((num_pages, page_size, cfg.n_kv_heads,
                       cfg.head_dim), dtype=dtype),
            jnp.zeros((num_pages, page_size, cfg.n_kv_heads,
                       cfg.head_dim), dtype=dtype),
        )
        for _ in range(cfg.n_layers)
    ]
    axis = page_pool_axis(mesh)
    if axis is not None:
        # Page-axis sharding (PR 20): each slice member holds a
        # num_pages/shards sub-pool — per-device sub-pools under the
        # ONE host-side reservation invariant (_PagePool still
        # accounts the full pool; GSPMD routes each page's reads and
        # writes to the member that owns it).
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(axis))
        pools = [(jax.device_put(k, sharding), jax.device_put(v, sharding))
                 for k, v in pools]
    return pools


def page_pool_nbytes(cfg: LlmConfig, num_pages: int, page_size: int,
                     dtype=None) -> int:
    """Analytic size of the init_page_pool slab (K and V per layer):
    what the HBM allocator admits BEFORE the device arrays exist, so
    an over-budget slab sheds honestly instead of OOMing mid-zeros."""
    dtype = jnp.dtype(dtype or cfg.dtype)
    per_pool = (int(num_pages) * int(page_size) * cfg.n_kv_heads
                * cfg.head_dim * dtype.itemsize)
    return 2 * cfg.n_layers * per_pool


def prefix_page_hashes(prompt, page_size: int) -> List[bytes]:
    """Chained BLAKE2b digest per FULL page of prompt tokens: digest
    ``p`` covers tokens ``[0, (p+1) * page_size)`` — a page's K/V
    depend on the whole prefix through attention, so the hash must
    too (the PR-5 content-hash approach at page granularity)."""
    arr = np.asarray(prompt, dtype=np.int32)
    running = hashlib.blake2b(digest_size=16)
    out: List[bytes] = []
    for p in range(len(arr) // page_size):
        running.update(arr[p * page_size:(p + 1) * page_size].tobytes())
        out.append(running.digest())
    return out


def _paged_block(layer, x, positions, mask, cfg: LlmConfig, kv, dest,
                 tables, page_size: int):
    """One transformer block over the paged pool: write this call's
    K/V rows at flat slots ``dest`` (sentinel rows dropped), then
    attend over the lane's block-table gather. x ``[B,S,D]``, dest
    ``[B*S]``, tables ``[B,P]``, kv = (K pool, V pool)."""
    ck, cv = kv
    h = _rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    b, s = x.shape[0], x.shape[1]
    flat_k = ck.reshape((-1,) + ck.shape[2:])
    flat_v = cv.reshape((-1,) + cv.shape[2:])
    flat_k = flat_k.at[dest].set(
        k.reshape((b * s,) + k.shape[2:]), mode="drop")
    flat_v = flat_v.at[dest].set(
        v.reshape((b * s,) + v.shape[2:]), mode="drop")
    ck = flat_k.reshape(ck.shape)
    cv = flat_v.reshape(cv.shape)
    t = tables.shape[1] * page_size
    gk = ck[tables].reshape((b, t) + ck.shape[2:])
    gv = cv[tables].reshape((b, t) + cv.shape[2:])
    ctx = _attention(q, gk, gv, mask)
    x = x + jnp.einsum("bshk,hkd->bsd", ctx, layer["wo"])
    h = _rms_norm(x, layer["mlp_norm"])
    gated = jax.nn.silu(h @ layer["w_gate"]) * (h @ layer["w_up"])
    return x + gated @ layer["w_down"], (ck, cv)


def paged_decode_chunk(params, tokens, pos, limit, eos_stop, done,
                       tables, pool, *, cfg: LlmConfig, length: int,
                       page_size: int):
    """Greedy-decodes up to ``length`` tokens for B lanes against the
    paged pool. tokens/pos/limit ``[B]``; eos_stop/done ``[B]`` bool;
    tables ``[B, P]`` page ids. Per-lane masking fixes the run-ahead
    waste the dense arm pays: a lane decodes only while
    ``step < limit`` (host-known budget) and ``not done`` (device-known
    EOS, carried BETWEEN dispatches) — an in-flight chunk dispatched
    before the host learned of a lane's EOS writes nothing for that
    lane and burns no pages. Returns
    ``(emitted [length, B], tokens [B], done [B], pool)``; inactive
    steps emit PAD."""
    num_slots = pool[0][0].shape[0] * page_size
    t_width = tables.shape[1] * page_size

    def step(carry, i):
        tok, p, dn, pl = carry
        active = jnp.logical_and(jnp.logical_not(dn), i < limit)
        x = params["embed"][tok[:, None]]  # [B,1,D]
        positions = p[:, None]
        page = jnp.take_along_axis(
            tables, (p // page_size)[:, None], axis=1)[:, 0]
        dest = jnp.where(active, page * page_size + p % page_size,
                         num_slots)
        mask = jnp.arange(t_width)[None, None, :] <= p[:, None, None]
        new_pool = []
        for layer, kv in zip(params["layers"], pl):
            x, kv = _paged_block(layer, x, positions, mask, cfg, kv,
                                 dest, tables, page_size)
            new_pool.append(kv)
        x = _rms_norm(x, params["final_norm"])
        logits = (x[:, -1] @ params["unembed"]).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        newly_done = jnp.logical_and(
            active, jnp.logical_and(nxt == EOS, eos_stop))
        emit = jnp.where(active, nxt, PAD)
        tok = jnp.where(active, nxt, tok)
        p = jnp.where(active, p + 1, p)
        dn = jnp.logical_or(dn, newly_done)
        return (tok, p, dn, tuple(new_pool)), emit

    (tok, _, done, pool), emitted = jax.lax.scan(
        step,
        (tokens.astype(jnp.int32), pos.astype(jnp.int32), done,
         tuple(pool)),
        jnp.arange(length))
    return emitted, tok, done, list(pool)


def paged_prefill_chunk(params, tokens, positions, dest, last_row,
                        tables, pool, *, cfg: LlmConfig,
                        page_size: int):
    """One bounded prefill chunk for a single joining sequence:
    tokens ``[1, C]``, positions ``[C]`` (absolute, ``start+i``), dest
    ``[C]`` flat pool slots (sentinel for padded rows AND rows covered
    by shared prefix pages — copy-on-write: shared pages are never
    written), tables ``[1, P]`` covering the lane's pages so far.
    Attention gathers the whole live context (earlier chunks + shared
    prefix pages) from the pool. Returns the greedy next token after
    row ``last_row`` (``[1]``, meaningful on the final chunk) and the
    updated pool."""
    t_width = tables.shape[1] * page_size
    x = params["embed"][tokens]  # [1,C,D]
    posb = positions[None, :]
    mask = (jnp.arange(t_width)[None, None, :]
            <= positions[None, :, None])  # [1,C,T]
    new_pool = []
    for layer, kv in zip(params["layers"], pool):
        x, kv = _paged_block(layer, x, posb, mask, cfg, kv, dest,
                             tables, page_size)
        new_pool.append(kv)
    x = _rms_norm(x, params["final_norm"])
    last = jax.lax.dynamic_slice_in_dim(x[0], last_row, 1, axis=0)[0]
    logits = (last @ params["unembed"]).astype(jnp.float32)
    return jnp.argmax(logits).astype(jnp.int32).reshape(1), new_pool


def pack_pages(pool, scratch, dest):
    """Scatters a batched scratch prefill cache (``[b, bucket, ...]``
    per layer) into pool pages at flat slots ``dest [b * bucket]``
    (sentinel rows — padding — are dropped)."""
    out = []
    for (pk, pv), (sk, sv) in zip(pool, scratch):
        fk = pk.reshape((-1,) + pk.shape[2:])
        fv = pv.reshape((-1,) + pv.shape[2:])
        fk = fk.at[dest].set(
            sk.reshape((-1,) + sk.shape[2:]), mode="drop")
        fv = fv.at[dest].set(
            sv.reshape((-1,) + sv.shape[2:]), mode="drop")
        out.append((fk.reshape(pk.shape), fv.reshape(pv.shape)))
    return out


class _PagePool:
    """Host-side page accounting (guarded by the model's scheduler
    lock — no internal lock). Three invariant-bearing counts:

    * ``reserved`` — pages promised to admitted-but-not-yet-drawn
      work. Admission reserves a sequence's worst case
      (private prompt pages + decode pages for ``max_tokens``), so a
      mid-stream allocation can NEVER fail — the deadlock a
      free-for-all paged pool invites is ruled out by construction.
    * ``lane_held`` — private pages referenced by a live lane.
    * ``shared_live`` — prefix-cache pages pinned by >=1 live lane
      (copy-on-write refcounts; never written after registration).

    Pages whose only reference is the prefix index are EVICTABLE
    (LRU): they keep serving prefix hits while free, and are reclaimed
    on demand, so the admission invariant is
    ``reserved + lane_held + shared_live <= num_pages``."""

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._lane_refs = [0] * self.num_pages
        self._hash_of: Dict[int, bytes] = {}
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self.reserved = 0
        self.lane_held = 0
        self.shared_live = 0

    # -- admission ------------------------------------------------------

    def peek_chain(self, hashes: List[bytes], cap: int):
        """(hits, newly_pinned) for the longest cached prefix-page
        chain (<= cap pages) without attaching."""
        hits = pinned = 0
        for digest in hashes[:cap]:
            page = self._index.get(digest)
            if page is None:
                break
            hits += 1
            if self._lane_refs[page] == 0:
                pinned += 1
        return hits, pinned

    def can_admit(self, reserve_need: int, newly_pinned: int) -> bool:
        return (self.reserved + self.lane_held + self.shared_live
                + reserve_need + newly_pinned) <= self.num_pages

    def reserve(self, n: int) -> None:
        self.reserved += n

    def release_reservation(self, n: int) -> None:
        self.reserved -= n

    def attach(self, hashes: List[bytes]) -> List[int]:
        """Increfs the cached pages for ``hashes`` (all must be
        present — call peek_chain first) and returns their page ids
        in chain order."""
        pages = []
        for digest in hashes:
            page = self._index[digest]
            self._index.move_to_end(digest)
            if self._lane_refs[page] == 0:
                self.shared_live += 1
            self._lane_refs[page] += 1
            pages.append(page)
        return pages

    def alloc(self, n: int) -> List[int]:
        """Draws ``n`` private pages against the reservation, evicting
        LRU cache-only pages as needed. The admission invariant
        guarantees success; a failure is a refcount bug and raises."""
        if n > self.reserved:
            raise RuntimeError(
                "kv page alloc of %d exceeds reservation %d"
                % (n, self.reserved))
        out = []
        for _ in range(n):
            if not self._free:
                self._evict_one()
            page = self._free.pop()
            self._lane_refs[page] = 1
            self.lane_held += 1
            self.reserved -= 1
            out.append(page)
        return out

    def _evict_one(self) -> None:
        for digest, page in self._index.items():
            if self._lane_refs[page] == 0:
                del self._index[digest]
                del self._hash_of[page]
                self._free.append(page)
                return
        raise RuntimeError(
            "kv page pool invariant violated: no free or evictable "
            "page (reserved=%d lane_held=%d shared_live=%d)"
            % (self.reserved, self.lane_held, self.shared_live))

    def register(self, digest: bytes, page: int) -> None:
        """Publishes a lane-held page into the prefix index (becomes
        shared + copy-on-write; the write barrier is that nothing ever
        scatters to an indexed page again)."""
        if digest in self._index or page in self._hash_of:
            return
        self._index[digest] = page
        self._hash_of[page] = digest
        if self._lane_refs[page] > 0:
            self.lane_held -= 1
            self.shared_live += 1

    def free(self, pages: List[int]) -> None:
        for page in pages:
            self._lane_refs[page] -= 1
            if self._lane_refs[page] == 0:
                if page in self._hash_of:
                    self.shared_live -= 1  # stays cached, evictable
                else:
                    self.lane_held -= 1
                    self._free.append(page)

    def drop_cache(self) -> None:
        """Evicts every cache-only page (tests / leak accounting)."""
        for digest in [d for d, p in self._index.items()
                       if self._lane_refs[p] == 0]:
            page = self._index.pop(digest)
            del self._hash_of[page]
            self._free.append(page)

    def snapshot(self) -> dict:
        cached = len(self._index) - self.shared_live
        return {
            "pages_total": self.num_pages,
            "pages_used": self.lane_held + self.shared_live,
            "pages_cached": cached,
            "pages_free": len(self._free),
            "pages_reserved": self.reserved,
        }


def loss_fn(params, tokens, targets, cfg: LlmConfig, attention_fn=None):
    logits = forward(params, tokens, cfg, attention_fn=attention_fn)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    mask = (targets != PAD).astype(jnp.float32)
    return jnp.sum(nll[..., 0] * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def train_step(params, tokens, targets, cfg: LlmConfig, lr: float = 1e-3,
               attention_fn=None):
    """SGD training step (forward + backward + update) — the function
    the multi-chip dryrun jits over the mesh. ``attention_fn`` selects
    the attention op (ring attention for context-parallel runs)."""
    loss, grads = jax.value_and_grad(
        partial(loss_fn, cfg=cfg, attention_fn=attention_fn))(
        params, tokens, targets
    )
    new_params = jax.tree.map(
        lambda w, g: (w - lr * g.astype(w.dtype)).astype(w.dtype),
        params, grads,
    )
    return new_params, loss


# -- served model ----------------------------------------------------------


class _GenRequest:
    """One in-flight generation riding a decode lane."""

    def __init__(self, prompt, max_tokens: int, ignore_eos: bool):
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.ignore_eos = ignore_eos
        self.delivered = 0
        self.queue: queue.Queue = queue.Queue()
        self.error: Optional[str] = None
        self.error_status = "INTERNAL"
        # Set when the consumer abandons the stream (client
        # disconnect): the scheduler frees the lane at the next chunk
        # boundary instead of decoding the full budget into nowhere.
        self.cancelled = False
        # Paged-path bookkeeping: wall-clock admission deadline for the
        # join-queue page wait (PR-2 queue-deadline semantics), the
        # enqueue stamp feeding the page-free-time EWMA, and the
        # prompt's chained page hashes (computed ONCE at enqueue — a
        # blocked queue head is re-planned every scheduler pass).
        self.deadline_ns: Optional[int] = None
        self.enqueue_ns: Optional[int] = None
        self.page_hashes: List[bytes] = []

    def finish(self):
        self.queue.put(None)

    def fail(self, message: str, status: str = "INTERNAL"):
        self.error = message
        self.error_status = status
        self.queue.put(None)


class _PrefillJob:
    """A joining sequence whose prompt prefills in bounded chunks
    interleaved with decode steps (long prompts, and any prompt with a
    shared-prefix hit — the chunk kernel gathers the shared pages)."""

    __slots__ = ("lane", "req", "prompt", "done_tokens", "hashes")

    def __init__(self, lane: int, req: _GenRequest, prompt,
                 done_tokens: int, hashes: List[bytes]):
        self.lane = lane
        self.req = req
        self.prompt = prompt
        self.done_tokens = done_tokens  # shared-prefix tokens skipped
        self.hashes = hashes


class LlmModel(ServedModel):
    """Decoupled generate endpoint: text in, token stream out.

    Inputs: text_input BYTES [1]; max_tokens INT32 [1] (optional);
    outputs: text_output BYTES [1] per streamed response. Greedy
    decoding with multi-lane batched decode: a scheduler thread steps
    ``decode_lanes`` independent sequences through one jitted decode
    dispatch, so concurrent requests share device work instead of
    serializing (continuous batching at chunk granularity — requests
    join/leave at chunk boundaries).

    Two KV-cache arms (``paged_kv``, default True; docs/llm_serving.md):

    * **paged** — a device page pool (``[kv_pages, page_size, Hkv, D]``
      per layer) + per-lane block tables. HBM and attention width
      scale with live tokens (tables bucket to the longest live
      sequence), so ``decode_lanes`` can grow to 32-64; prompts
      prefill in bounded chunks interleaved with decode (chunked
      prefill), full prompt pages are content-hashed and shared
      copy-on-write across lanes (prefix cache), joins that cannot
      reserve pages wait bounded by their queue deadline, and past
      ``join_watermark`` arrivals shed with an honest Retry-After.
    * **dense** (``paged_kv=False``, the A/B baseline arm) — the
      legacy per-lane ``[lanes, max_seq, Hkv, D]`` cache: every lane
      reserves (and attends over) max_seq regardless of actual length.
      Paged decode is token-exact against this arm.

    The decode pipeline is split into a dispatch side (scheduler
    thread: prefills + decode chunks launched back-to-back, last
    tokens carried ON DEVICE between chunks) and a delivery side
    (delivery thread: waits on each chunk's pooled device->host fetch
    in dispatch order and routes tokens to requests). Up to
    MAX_INFLIGHT chunks are in flight, so the host-fetch round trip
    (~65 ms through this image's relay, real on any PCIe/ICI hop)
    overlaps decode compute instead of stalling the token stream every
    STREAM_CHUNK tokens — inter-token latency at a chunk boundary is
    the chunk's compute time, not the fetch latency.
    """

    decoupled = True
    platform = "jax"
    # Tokens per device-side decode dispatch (and per host fetch).
    STREAM_CHUNK = 8
    # Decode chunks allowed in flight (dispatched, fetch pending).
    # Pipelining bound: the relay's ~65 ms fetch overlaps roughly
    # fetch_latency / chunk_compute (~4) chunks; beyond that it is
    # queue-drain latency ahead of every join's first token. (The
    # dense arm also pays run-ahead waste on finished requests here;
    # the paged arm does not — per-lane limit/done masking means an
    # in-flight chunk never decodes a dead lane, see
    # paged_decode_chunk.)
    MAX_INFLIGHT = 5

    def __init__(self, name: str = "llm", cfg: Optional[LlmConfig] = None,
                 mesh=None, rules: ShardingRules = LLM_RULES,
                 seed: int = 0, decode_lanes: int = 4,
                 paged_kv: Optional[bool] = None, page_size: int = 16,
                 kv_pages: Optional[int] = None,
                 prefill_chunk: int = 64,
                 join_watermark: Optional[int] = None,
                 queue_timeout_s: float = 30.0):
        super().__init__()
        self.name = name
        self.cfg = cfg or LlmConfig()
        self._tokenizer = ByteTokenizer()
        self.inputs = [
            TensorSpec("text_input", "BYTES", [1]),
            TensorSpec("max_tokens", "INT32", [1], optional=True),
            TensorSpec("ignore_eos", "BOOL", [1], optional=True),
        ]
        self.outputs = [TensorSpec("text_output", "BYTES", [1])]

        key = jax.random.PRNGKey(seed)
        params = init_params(key, self.cfg)
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding

            specs = param_specs(self.cfg, rules)
            params = jax.tree.map(
                lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
                params, specs,
                is_leaf=lambda x: isinstance(x, jnp.ndarray),
            )
        self._params = params
        cfg_static = self.cfg

        def _prefill_first(p, t, c, n):
            # argmax folded in: the scheduler only needs the first
            # TOKEN, and a separate jitted argmax would compile per
            # batch shape mid-serving.
            logits, new_cache = prefill(p, t, c, cfg_static, true_len=n)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), new_cache

        self._prefill = jax.jit(_prefill_first)
        self._decode_chunk_multi = jax.jit(
            lambda p, tok, pos, c: decode_chunk_multi(
                p, tok, pos, c, cfg_static, self.STREAM_CHUNK),
            donate_argnums=(3,),
        )
        # Inserts row `b` of a batched prefill cache into lane `i` of
        # the decode cache (b and i are traced: one compile serves
        # every (row, lane) pair).
        self._lane_insert_row = jax.jit(
            lambda batched, multi, b, i: jax.tree.map(
                lambda dst, src: jax.lax.dynamic_update_slice(
                    dst, jax.lax.dynamic_slice_in_dim(src, b, 1, axis=0),
                    (i, 0, 0, 0)),
                batched, multi),
            donate_argnums=(0,),
        )
        # Scatter first tokens of joining lanes into the device-side
        # last-token vector the next decode chunk consumes.
        self._set_lane_tokens = jax.jit(
            lambda toks, idx, vals: toks.at[idx].set(vals),
            donate_argnums=(0,),
        )

        # Prefill executables keyed by (batch, bucket). Batched-join
        # prefill shapes are compiled AHEAD in a background thread the
        # first time a new shape shows up — an inline compile (seconds)
        # would stall every active token stream; until the compile
        # lands, joins fall back to the already-compiled batch-1 path.
        self._prefill_exec: Dict[tuple, object] = {}
        self._prefill_compiling: set = set()
        self._prefill_exec_lock = threading.Lock()

        self._lanes = max(1, int(decode_lanes))
        self._sched_lock = threading.Lock()
        self._sched_cv = threading.Condition(self._sched_lock)
        self._sched_thread: Optional[threading.Thread] = None
        self._delivery_thread: Optional[threading.Thread] = None
        self._fetch_pool = None
        self._sched_stop = False
        self._gen = 0  # bumped on crash: stale threads exit
        self._join_queue: list = []
        self._active: Dict[int, _GenRequest] = {}
        self._free_lanes = list(range(self._lanes))
        self._lane_pos = [0] * self._lanes  # host bookkeeping
        self._tokens_dev = None  # [lanes] int32 device carry
        self._batched_cache = None
        self._delivery_queue: deque = deque()
        self._inflight = 0  # dispatched-not-yet-delivered decode chunks

        # -- paged KV cache (the default serving arm; paged_kv=False
        # keeps the dense per-lane cache as the A/B baseline). PR 20
        # retired the mesh-sharded dense fallback: sharded deployments
        # serve paged too, with the pool's page axis sharded across
        # the slice (see init_page_pool).
        self._paged = bool(True if paged_kv is None else paged_kv)
        self._page_size = max(1, int(page_size))
        self._pages_per_seq = -(-self.cfg.max_seq // self._page_size)
        self._num_pages = (int(kv_pages) if kv_pages
                           else self._lanes * self._pages_per_seq)
        # Page-axis sharding wants an even split: round the pool UP to
        # a multiple of the shard count (extra pages are capacity, not
        # waste — the reservation invariant covers them too).
        kv_shards = page_axis_shards(mesh)
        if kv_shards > 1:
            self._num_pages = -(-self._num_pages // kv_shards) * kv_shards
        self._prefill_chunk = max(self._page_size,
                                  min(int(prefill_chunk),
                                      self.cfg.max_seq))
        self._join_watermark = (int(join_watermark) if join_watermark
                                else max(2 * self._lanes, 8))
        self._queue_timeout_s = float(queue_timeout_s)
        self._pool: Optional[_PagePool] = None  # host accounting
        self._pool_dev = None  # per-layer (K, V) page arrays
        # Device-ledger row for the page pool's HBM (kv_pages): held
        # while _pool_dev is live, released on crash rebuild / unload
        # so cross-model HBM accounting never shows a dead pool.
        self._kv_ledger_row = None
        # HBM-allocator leases for the slab (docs/hbm.md): carved
        # through budgeted admission in _ensure_page_pool — each lease
        # registers its own ledger row, so only leases/_kv_ledger_row
        # are ever live, never both. Unsharded = one lease
        # ("kv_pages"); mesh-sharded = one per member device
        # ("kv_pages:<device>"), each booked on ITS device's budget.
        self._kv_leases: list = []
        # Serializes slab admission OUTSIDE _sched_cv: allocator
        # admission may evict cold weights (device<->host transfers
        # that must never run under the scheduler's condition
        # variable). Deliberately not lockish-named — transfers under
        # it are the point.
        self._pool_admission = threading.Lock()
        self._done_dev = None  # [lanes] bool device carry (EOS latch)
        self._lane_pages: List[List[int]] = [
            [] for _ in range(self._lanes)]
        self._lane_reserved = [0] * self._lanes
        self._lane_steps_left = [0] * self._lanes
        self._prefill_jobs: List[_PrefillJob] = []
        self._joining: List[_GenRequest] = []  # admitted, not yet active
        self._ewma_request_s: Optional[float] = None
        self._kv_counters = {
            "prefix_hits_total": 0,
            "prefill_chunks_total": 0,
            "shed_total": 0,
            "expired_total": 0,
            "pages_used_peak": 0,
        }
        if self._paged:
            self._paged_decode = jax.jit(
                partial(paged_decode_chunk, cfg=cfg_static,
                        length=self.STREAM_CHUNK,
                        page_size=self._page_size),
                donate_argnums=(7,))
            self._paged_prefill = jax.jit(
                partial(paged_prefill_chunk, cfg=cfg_static,
                        page_size=self._page_size),
                donate_argnums=(6,))
            self._pack_pages = jax.jit(pack_pages, donate_argnums=(0,))
            self._gather_lanes = jax.jit(
                lambda toks, done, idx: (toks[idx], done[idx]))
            # Pad rows scatter to index `lanes` (out of bounds) and drop.
            self._scatter_lanes = jax.jit(
                lambda toks, done, idx, tv, dv: (
                    toks.at[idx].set(tv, mode="drop"),
                    done.at[idx].set(dv, mode="drop")),
                donate_argnums=(0, 1))
            # Join commit: seat first tokens + clear the EOS latch.
            self._join_lanes = jax.jit(
                lambda toks, done, idx, vals: (
                    toks.at[idx].set(vals),
                    done.at[idx].set(False)),
                donate_argnums=(0, 1))

    # -- scheduler -------------------------------------------------------

    def _ensure_scheduler(self):
        with self._sched_cv:
            if self._sched_stop:
                return
            if self._fetch_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                # Sized so every in-flight chunk's device->host fetch
                # overlaps (the relay pipelines concurrent fetches:
                # 8 concurrent transfers complete in one ~65 ms round
                # trip, measured on this image).
                self._fetch_pool = ThreadPoolExecutor(
                    max_workers=self.MAX_INFLIGHT + 2,
                    thread_name_prefix="llm-fetch-%s" % self.name)
            if self._sched_thread is None:
                loop = (self._scheduler_loop_paged if self._paged
                        else self._scheduler_loop)
                self._sched_thread = threading.Thread(
                    target=loop, args=(self._gen,),
                    daemon=True, name="llm-decode-%s" % self.name)
                self._sched_thread.start()
            if self._delivery_thread is None:
                self._delivery_thread = threading.Thread(
                    target=self._delivery_loop, args=(self._gen,),
                    daemon=True, name="llm-deliver-%s" % self.name)
                self._delivery_thread.start()

    def _deliver(self, lane: int, req: _GenRequest, token: int) -> bool:
        """Pushes one token; returns False when the request finished
        (EOS, budget, or consumer abandonment). Caller holds
        _sched_cv."""
        if req.cancelled:
            req.finish()
            return False
        if token == EOS and not req.ignore_eos:
            req.finish()
            return False
        req.queue.put(int(token))
        req.delivered += 1
        if req.delivered >= req.max_tokens:
            req.finish()
            return False
        return True

    def _release_lane(self, lane: int):
        """Caller holds _sched_cv. On the paged arm this is also where
        the lane's pages and leftover reservation return to the pool
        (shared prefix pages decref; private pages free immediately —
        stale in-flight writes to a recycled page are harmless because
        every dispatch is device-stream-ordered and a page's next
        owner writes, or masks, each row before attending to it)."""
        req = self._active.pop(lane, None)
        self._lane_pos[lane] = 0
        if self._paged:
            self._free_lane_pages(lane)
            if req is not None and req.enqueue_ns is not None:
                dur_s = (time.monotonic_ns() - req.enqueue_ns) / 1e9
                if self._ewma_request_s is None:
                    self._ewma_request_s = dur_s
                else:
                    self._ewma_request_s = (0.7 * self._ewma_request_s
                                            + 0.3 * dur_s)
        self._free_lanes.append(lane)

    def _free_lane_pages(self, lane: int):
        """Caller holds _sched_cv."""
        if self._pool is not None:
            self._pool.free(self._lane_pages[lane])
            self._pool.release_reservation(self._lane_reserved[lane])
        self._lane_pages[lane] = []
        self._lane_reserved[lane] = 0
        self._lane_steps_left[lane] = 0

    def _compile_prefill(self, b: int, bucket: int):
        """AOT-compiles the (b, bucket) prefill and publishes it in
        _prefill_exec. Runs inline for batch 1 (first use of a new
        bucket has nothing to fall back to) and on a background thread
        for batched shapes."""
        toks = jax.ShapeDtypeStruct((b, bucket), jnp.int32)
        lens = jax.ShapeDtypeStruct((b,), jnp.int32)
        # Paged arm prefills into a bucket-sized scratch cache (packed
        # into pages afterwards) instead of a max_seq reservation.
        cache = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            init_cache(self.cfg, b,
                       length=bucket if self._paged else None))
        compiled = self._prefill.lower(
            self._params, toks, cache, lens).compile()
        with self._prefill_exec_lock:
            self._prefill_exec[(b, bucket)] = compiled
            self._prefill_compiling.discard((b, bucket))

    def _get_prefill_exec(self, b: int, bucket: int):
        """Returns the compiled (b, bucket) prefill, or None while a
        background compile is still in flight (caller falls back to
        batch 1). Batch 1 always blocks until compiled."""
        key = (b, bucket)
        with self._prefill_exec_lock:
            compiled = self._prefill_exec.get(key)
            if compiled is not None:
                return compiled
            if b > 1 and key in self._prefill_compiling:
                return None
            if b > 1:
                self._prefill_compiling.add(key)
        if b == 1:
            self._compile_prefill(1, bucket)
            return self._prefill_exec[key]
        threading.Thread(
            target=self._compile_prefill_safely, args=(b, bucket),
            daemon=True, name="llm-prefill-compile").start()
        return None

    def _compile_prefill_safely(self, b: int, bucket: int):
        self._attribute_thread()
        try:
            self._compile_prefill(b, bucket)
        except Exception:  # noqa: BLE001 — joins keep falling back
            with self._prefill_exec_lock:
                self._prefill_compiling.discard((b, bucket))

    def _dispatch_joins(self, joins, gen: int):
        """Batched prefill for a set of (lane, request) joins: prompts
        sharing a padded bucket go through ONE prefill dispatch (batch
        padded to a power of two so XLA compiles per (B, bucket), not
        per request mix), their caches are row-inserted into the
        decode cache, and the first tokens are scattered into the
        device token vector. Nothing here blocks on the device — the
        first tokens travel to clients through the delivery queue like
        any decode chunk. Runs on the scheduler thread, no lock held
        during device work."""
        groups: Dict[int, list] = {}
        for lane, req in joins:
            n = len(req.prompt)
            bucket = 16
            while bucket < n:
                bucket *= 2
            bucket = min(bucket, self.cfg.max_seq)
            groups.setdefault(bucket, []).append((lane, req))
        batches = []
        for bucket, group in groups.items():
            b = 1
            while b < len(group):
                b *= 2
            compiled = self._get_prefill_exec(b, bucket)
            if compiled is None:
                # Batched shape still compiling in the background:
                # fall back to batch-1 prefills rather than stalling
                # every active stream for seconds.
                one = self._get_prefill_exec(1, bucket)
                batches.extend((bucket, 1, one, [entry]) for entry in group)
            else:
                batches.append((bucket, b, compiled, group))
        for batch_idx, (bucket, b, compiled, group) in enumerate(batches):
            padded = np.full((b, bucket), PAD, dtype=np.int32)
            lens = np.ones((b,), dtype=np.int32)
            for row, (lane, req) in enumerate(group):
                padded[row, :len(req.prompt)] = req.prompt
                lens[row] = len(req.prompt)
            firsts, multi_cache = compiled(
                self._params, jnp.asarray(padded),
                init_cache(self.cfg, b), jnp.asarray(lens))  # [b] device
            lanes_idx = np.array([lane for lane, _ in group],
                                 dtype=np.int32)
            # Row-insert into locals; publish under the lock only after
            # the gen check below — a concurrent _crash rebuilds the
            # cache/token carry and an unlocked old-generation rebind
            # here would clobber the new generation's fresh state.
            with self._sched_cv:
                cache = self._batched_cache
                tokens_dev = self._tokens_dev
            for row, (lane, req) in enumerate(group):
                cache = self._lane_insert_row(
                    cache, multi_cache, np.int32(row), np.int32(lane))
            tokens_dev = self._set_lane_tokens(
                tokens_dev, jnp.asarray(lanes_idx), firsts[:len(group)])
            fut = self._fetch_pool.submit(np.asarray, firsts)
            with self._sched_cv:
                if self._sched_stop or self._gen != gen:
                    # Unload or a concurrent _crash reset the pipeline.
                    # Fail the current group AND every not-yet-run
                    # group — they are all popped off _join_queue and
                    # invisible to any other cleanup path. After a
                    # crash the lane list was already rebuilt, so only
                    # re-add lanes while this generation is live.
                    for _, _, _, late_group in batches[batch_idx:]:
                        for lane, req in late_group:
                            req.fail("model unloaded")
                            if self._gen == gen:
                                self._free_lanes.append(lane)
                    return
                self._batched_cache = cache
                self._tokens_dev = tokens_dev
                for row, (lane, req) in enumerate(group):
                    self._lane_pos[lane] = len(req.prompt)
                    self._active[lane] = req
                self._delivery_queue.append(("join", fut, list(group)))
                self._sched_cv.notify_all()

    def _scheduler_loop(self, gen: int):
        """Dispatch side of the decode pipeline: prefills joins and
        launches decode chunks back-to-back WITHOUT waiting for their
        device->host fetches — each chunk's token fetch rides the
        fetch pool and reaches clients through _delivery_loop. The
        relay's ~65 ms fetch latency then overlaps the next chunks'
        compute instead of gating the token cadence (inter-chunk gap =
        chunk compute time, not fetch latency)."""
        self._attribute_thread()
        try:
            while True:
                joins = []
                with self._sched_cv:
                    while (not self._sched_stop and self._gen == gen
                           and not (self._join_queue and self._free_lanes)
                           and not (self._active
                                    and self._inflight < self.MAX_INFLIGHT)):
                        self._sched_cv.wait()
                    if self._sched_stop or self._gen != gen:
                        return
                    while self._join_queue and self._free_lanes:
                        req = self._join_queue.pop(0)
                        if req.cancelled:  # abandoned while queued
                            req.finish()
                            continue
                        joins.append((self._free_lanes.pop(0), req))
                if joins:
                    try:
                        self._dispatch_joins(joins, gen)
                    except Exception as e:  # noqa: BLE001
                        # Popped requests are in neither _active nor
                        # _join_queue, so the crash handler cannot see
                        # all of them — fail them here or their clients
                        # block forever on queue.get().
                        with self._sched_cv:
                            for lane2, req2 in joins:
                                if self._active.get(lane2) is not req2:
                                    req2.fail("llm prefill failed: %s" % e)
                                    if (self._gen == gen
                                            and lane2 not in self._active):
                                        self._free_lanes.append(lane2)
                        raise
                    continue  # more joins may fit before the next chunk
                with self._sched_cv:
                    if (not self._active or self._batched_cache is None
                            or self._inflight >= self.MAX_INFLIGHT):
                        continue
                    pos_host = np.asarray(self._lane_pos, dtype=np.int32)
                    params = self._params
                    tokens_dev = self._tokens_dev
                    cache = self._batched_cache
                toks, new_cache = self._decode_chunk_multi(
                    params, tokens_dev, jnp.asarray(pos_host), cache)
                fut = self._fetch_pool.submit(np.asarray, toks)
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        # A concurrent _crash/unload reset the pipeline
                        # while this dispatch ran unlocked — registering
                        # the record would hand the NEW generation a
                        # stale (possibly failing) future, re-mark
                        # rebuilt free lanes active, or clobber the new
                        # generation's freshly rebuilt cache/token carry
                        # with this old generation's outputs.
                        return
                    self._batched_cache = new_cache
                    self._tokens_dev = toks[-1]  # [lanes] device carry
                    snapshot = {lane: (req, self.STREAM_CHUNK, lane)
                                for lane, req in self._active.items()}
                    for lane in snapshot:
                        self._lane_pos[lane] += self.STREAM_CHUNK
                    self._inflight += 1
                    self._delivery_queue.append(("chunk", fut, snapshot))
                    self._sched_cv.notify_all()
        except Exception as e:  # noqa: BLE001 — fail all riders loudly
            self._crash("llm scheduler failed: %s" % e, gen)

    def _delivery_loop(self, gen: int):
        """Consumer side of the decode pipeline: waits on each fetched
        token block IN DISPATCH ORDER and routes tokens to their
        requests. Runs concurrently with the scheduler's next
        dispatches, so the fetch latency is pipelined away."""
        try:
            while True:
                with self._sched_cv:
                    while (not self._sched_stop and self._gen == gen
                           and not self._delivery_queue):
                        self._sched_cv.wait()
                    if self._sched_stop or self._gen != gen:
                        return
                    kind, fut, payload = self._delivery_queue.popleft()
                ids = fut.result()  # blocks ~one relay round trip
                if kind == "join":
                    with self._sched_cv:
                        if self._gen != gen:
                            return
                        for row, (lane, req) in enumerate(payload):
                            if self._active.get(lane) is not req:
                                continue  # finished/cancelled already
                            if not self._deliver(lane, req, int(ids[row])):
                                self._release_lane(lane)
                        self._sched_cv.notify_all()
                    continue
                with self._sched_cv:
                    if self._gen != gen:
                        return
                    for lane, (req, steps, row) in payload.items():
                        if self._active.get(lane) is not req:
                            continue  # lane re-assigned since dispatch
                        alive = True
                        for token in ids[:steps, row]:
                            alive = self._deliver(lane, req, int(token))
                            if not alive:
                                break
                        if alive and (len(req.prompt) + req.delivered
                                      >= self.cfg.max_seq - 1):
                            req.finish()
                            alive = False
                        if not alive:
                            self._release_lane(lane)
                    self._inflight -= 1
                    self._sched_cv.notify_all()
        except Exception as e:  # noqa: BLE001
            self._crash("llm delivery failed: %s" % e, gen)

    # -- paged scheduler -------------------------------------------------

    def _page_wait_estimate_locked(self) -> float:
        """Honest page-free-time estimate for the shed Retry-After:
        the request-duration EWMA scaled by the queue's depth relative
        to the lane count. Caller holds _sched_cv."""
        base = self._ewma_request_s if self._ewma_request_s else 1.0
        waiting = len(self._join_queue) + 1
        return max(0.05, base * waiting / max(self._lanes, 1))

    def _plan_admission(self, req: _GenRequest):
        """Pages this join needs (worst case) and what the prefix
        cache already holds. Returns None when the pool cannot cover
        the reservation yet. Caller holds _sched_cv."""
        ps = self._page_size
        n = len(req.prompt)
        hashes = req.page_hashes
        # Never share the FINAL full page of an exactly page-aligned
        # prompt: its last-row logits seed the first token, so at
        # least one prompt row must be recomputed.
        shareable = len(hashes) - (1 if n % ps == 0 else 0)
        hits, newly_pinned = self._pool.peek_chain(hashes,
                                                   max(shareable, 0))
        total_slots = min(n + max(req.max_tokens - 1, 0),
                          self.cfg.max_seq)
        need = -(-total_slots // ps) - hits
        if not self._pool.can_admit(need, newly_pinned):
            return None
        return {"hashes": hashes, "hits": hits, "need": need}

    def _commit_admission(self, lane: int, req: _GenRequest,
                          plan: dict):
        """Caller holds _sched_cv."""
        shared = self._pool.attach(plan["hashes"][:plan["hits"]])
        self._pool.reserve(plan["need"])
        self._lane_pages[lane] = list(shared)
        self._lane_reserved[lane] = plan["need"]
        self._lane_steps_left[lane] = max(req.max_tokens - 1, 0)
        self._kv_counters["prefix_hits_total"] += plan["hits"]
        self._note_pages_peak()
        self._joining.append(req)

    def _note_pages_peak(self):
        used = self._pool.lane_held + self._pool.shared_live
        if used > self._kv_counters["pages_used_peak"]:
            self._kv_counters["pages_used_peak"] = used

    def _expire_queued_joins(self):
        """Fails queued joins whose PR-2-style queue deadline passed
        while waiting for pages. Caller holds _sched_cv."""
        now = time.monotonic_ns()
        keep = []
        for req in self._join_queue:
            if req.cancelled:
                req.finish()
            elif req.deadline_ns is not None and now > req.deadline_ns:
                self._kv_counters["expired_total"] += 1
                req.fail("model '%s': deadline exceeded waiting for KV "
                         "pages" % self.name,
                         status="DEADLINE_EXCEEDED")
            else:
                keep.append(req)
        self._join_queue[:] = keep

    def _next_deadline_delta_s(self) -> Optional[float]:
        """Seconds until the earliest queued-join deadline (the paged
        scheduler's idle-wait bound). Caller holds _sched_cv."""
        deadlines = [req.deadline_ns for req in self._join_queue
                     if req.deadline_ns is not None]
        if not deadlines:
            return None
        return max((min(deadlines) - time.monotonic_ns()) / 1e9, 0.01)

    def _admit_joins(self):
        """Pops admissible joins FIFO (strict order: a big join at the
        head is not overtaken — it would starve under a stream of
        small ones). Caller holds _sched_cv."""
        joins = []
        while self._join_queue and self._free_lanes:
            req = self._join_queue[0]
            if req.cancelled:
                self._join_queue.pop(0)
                req.finish()
                continue
            plan = self._plan_admission(req)
            if plan is None:
                break  # pages unavailable: wait (bounded by deadline)
            self._join_queue.pop(0)
            lane = self._free_lanes.pop(0)
            self._commit_admission(lane, req, plan)
            joins.append((lane, req, plan))
        return joins

    def _scheduler_loop_paged(self, gen: int):
        """Dispatch side of the paged decode pipeline. Each pass:
        admit joins (page-pool admission control), dispatch one decode
        chunk across every decodable lane, then at most ONE bounded
        prefill chunk — chunked prefill interleaves 1:1 with decode so
        a long-prompt join never spikes active streams' ITL the way
        the dense arm's all-at-once prefill dispatch does."""
        self._attribute_thread()
        try:
            while True:
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        return
                    self._expire_queued_joins()
                    joins = self._admit_joins()
                progressed = False
                if joins:
                    self._dispatch_joins_paged(joins, gen)
                    progressed = True
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        return
                progressed |= self._dispatch_decode_paged(gen)
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        return
                progressed |= self._dispatch_prefill_chunk(gen)
                with self._sched_cv:
                    if self._sched_stop or self._gen != gen:
                        return
                    if not progressed:
                        self._sched_cv.wait(
                            timeout=self._next_deadline_delta_s())
        except Exception as e:  # noqa: BLE001 — fail all riders loudly
            self._crash("llm scheduler failed: %s" % e, gen)

    def _dispatch_joins_paged(self, joins, gen: int):
        """Routes admitted joins: short prompts with no prefix hit go
        through ONE batched scratch prefill + page pack (bounded by
        prefill_chunk, so it cannot spike ITL); long prompts and
        prefix-hit prompts become chunked prefill jobs (the chunk
        kernel gathers shared pages from the pool)."""
        batched = []
        with self._sched_cv:
            if self._sched_stop or self._gen != gen:
                return
            for lane, req, plan in joins:
                if (plan["hits"] == 0
                        and len(req.prompt) <= self._prefill_chunk):
                    batched.append((lane, req, plan))
                else:
                    self._prefill_jobs.append(_PrefillJob(
                        lane, req, req.prompt,
                        plan["hits"] * self._page_size,
                        plan["hashes"]))
        if batched:
            self._dispatch_batched_prefill(batched, gen)

    def _activate_lane_locked(self, lane: int, req: _GenRequest):
        """Transition admitted -> active. Caller holds _sched_cv."""
        self._lane_pos[lane] = len(req.prompt)
        self._active[lane] = req
        if req in self._joining:
            self._joining.remove(req)

    def _register_prompt_pages_locked(self, lane: int,
                                      hashes: List[bytes]):
        """Publishes the lane's full prompt pages into the prefix
        index (they become shared/copy-on-write and outlive the lane
        as evictable cache entries)."""
        for i, digest in enumerate(hashes):
            if i < len(self._lane_pages[lane]):
                self._pool.register(digest, self._lane_pages[lane][i])

    def _dispatch_batched_prefill(self, group, gen: int):
        """Batched scratch prefill for short no-prefix-hit joins:
        prompts sharing a padded bucket run through ONE prefill
        dispatch into a bucket-sized scratch cache, which is then
        packed into each lane's freshly allocated pages."""
        ps = self._page_size
        groups: Dict[int, list] = {}
        for lane, req, plan in group:
            bucket = 16
            while bucket < len(req.prompt):
                bucket *= 2
            groups.setdefault(bucket, []).append((lane, req, plan))
        batches = []
        for bucket, entries in groups.items():
            b = 1
            while b < len(entries):
                b *= 2
            compiled = self._get_prefill_exec(b, bucket)
            if compiled is None:
                one = self._get_prefill_exec(1, bucket)
                batches.extend((bucket, 1, one, [entry])
                               for entry in entries)
            else:
                batches.append((bucket, b, compiled, entries))
        for bucket, b, compiled, entries in batches:
            padded = np.full((b, bucket), PAD, dtype=np.int32)
            lens = np.ones((b,), dtype=np.int32)
            sentinel = self._num_pages * ps
            dest = np.full((b * bucket,), sentinel, dtype=np.int32)
            with self._sched_cv:
                if self._sched_stop or self._gen != gen:
                    return
                for row, (lane, req, plan) in enumerate(entries):
                    n = len(req.prompt)
                    padded[row, :n] = req.prompt
                    lens[row] = n
                    pages = self._pool.alloc(-(-n // ps))
                    self._lane_reserved[lane] -= len(pages)
                    self._lane_pages[lane].extend(pages)
                    for i in range(n):
                        dest[row * bucket + i] = pages[i // ps] * ps \
                            + i % ps
                self._note_pages_peak()
                pool = self._pool_dev
                tokens_dev = self._tokens_dev
                done_dev = self._done_dev
            busy_t0 = time.monotonic_ns()
            firsts, scratch = compiled(
                self._params, jnp.asarray(padded),
                init_cache(self.cfg, b, length=bucket),
                jnp.asarray(lens))
            pool = self._pack_pages(pool, scratch, jnp.asarray(dest))
            lanes_idx = jnp.asarray(
                np.array([lane for lane, _, _ in entries],
                         dtype=np.int32))
            tokens_dev, done_dev = self._join_lanes(
                tokens_dev, done_dev, lanes_idx, firsts[:len(entries)])
            self._record_busy(busy_t0)
            fut = self._fetch_pool.submit(np.asarray,
                                          firsts[:len(entries)])
            with self._sched_cv:
                if self._sched_stop or self._gen != gen:
                    return  # riders already failed by crash/unload
                self._pool_dev = pool
                self._tokens_dev = tokens_dev
                self._done_dev = done_dev
                for lane, req, plan in entries:
                    self._activate_lane_locked(lane, req)
                    self._register_prompt_pages_locked(
                        lane, plan["hashes"])
                self._kv_counters["prefill_chunks_total"] += 1
                self._delivery_queue.append(
                    ("join", fut,
                     [(lane, req) for lane, req, _ in entries]))
                self._sched_cv.notify_all()

    def _dispatch_prefill_chunk(self, gen: int) -> bool:
        """Runs ONE bounded chunk of the oldest prefill job. Returns
        True when a dispatch happened."""
        ps = self._page_size
        chunk = self._prefill_chunk
        with self._sched_cv:
            if not self._prefill_jobs:
                return False
            job = self._prefill_jobs[0]
            if job.req.cancelled:
                self._prefill_jobs.pop(0)
                job.req.finish()
                if job.req in self._joining:
                    self._joining.remove(job.req)
                self._free_lane_pages(job.lane)
                self._free_lanes.append(job.lane)
                self._sched_cv.notify_all()
                return True
            n = len(job.prompt)
            tc = min(chunk, n - job.done_tokens)
            start = job.done_tokens
            need = -(-(start + tc) // ps) - len(self._lane_pages[job.lane])
            if need > 0:
                pages = self._pool.alloc(need)
                self._lane_reserved[job.lane] -= need
                self._lane_pages[job.lane].extend(pages)
                self._note_pages_peak()
            lane_pages = list(self._lane_pages[job.lane])
            pool = self._pool_dev
        sentinel = self._num_pages * ps
        tokens_chunk = np.full((1, chunk), PAD, dtype=np.int32)
        tokens_chunk[0, :tc] = job.prompt[start:start + tc]
        positions = (start + np.arange(chunk)).astype(np.int32)
        dest = np.full((chunk,), sentinel, dtype=np.int32)
        for i in range(tc):
            pos = start + i
            dest[i] = lane_pages[pos // ps] * ps + pos % ps
        p_bucket = 1
        while p_bucket < len(lane_pages):
            p_bucket *= 2
        tables = np.zeros((1, p_bucket), dtype=np.int32)
        tables[0, :len(lane_pages)] = lane_pages
        busy_t0 = time.monotonic_ns()
        first_dev, pool = self._paged_prefill(
            self._params, jnp.asarray(tokens_chunk),
            jnp.asarray(positions), jnp.asarray(dest),
            np.int32(tc - 1), jnp.asarray(tables), pool)
        self._record_busy(busy_t0)
        with self._sched_cv:
            if self._sched_stop or self._gen != gen:
                return True
            self._pool_dev = pool
            job.done_tokens += tc
            self._kv_counters["prefill_chunks_total"] += 1
            if job.done_tokens < n:
                return True
            self._prefill_jobs.pop(0)
            tokens_dev = self._tokens_dev
            done_dev = self._done_dev
        tokens_dev, done_dev = self._join_lanes(
            tokens_dev, done_dev,
            jnp.asarray(np.array([job.lane], dtype=np.int32)),
            first_dev)
        fut = self._fetch_pool.submit(np.asarray, first_dev)
        with self._sched_cv:
            if self._sched_stop or self._gen != gen:
                return True
            self._tokens_dev = tokens_dev
            self._done_dev = done_dev
            self._activate_lane_locked(job.lane, job.req)
            self._register_prompt_pages_locked(job.lane, job.hashes)
            self._delivery_queue.append(
                ("join", fut, [(job.lane, job.req)]))
            self._sched_cv.notify_all()
        return True

    def _dispatch_decode_paged(self, gen: int) -> bool:
        """One decode chunk across every decodable lane, compacted to
        a power-of-two batch and a power-of-two block-table width (so
        attention cost follows the LONGEST LIVE sequence, not
        max_seq). Returns True when a dispatch happened."""
        ps = self._page_size
        reaped = False
        with self._sched_cv:
            if (not self._active or self._pool_dev is None
                    or self._inflight >= self.MAX_INFLIGHT):
                return False
            rows = []
            for lane in sorted(self._active):
                req = self._active[lane]
                if req.cancelled:
                    # Cancel lands here, not at the next chunk
                    # boundary: the lane and its pages free NOW. This
                    # counts as progress — the freed pages may admit a
                    # queued join, so the loop must re-run admission
                    # instead of sleeping to that join's deadline.
                    req.finish()
                    self._release_lane(lane)
                    reaped = True
                    continue
                steps = min(self.STREAM_CHUNK,
                            self._lane_steps_left[lane],
                            self.cfg.max_seq - self._lane_pos[lane])
                if steps <= 0:
                    continue  # budget spent; awaiting delivery/finish
                rows.append((lane, req, steps))
            if not rows:
                return reaped
            for lane, req, steps in rows:
                need = (-(-(self._lane_pos[lane] + steps) // ps)
                        - len(self._lane_pages[lane]))
                if need > 0:
                    pages = self._pool.alloc(need)
                    self._lane_reserved[lane] -= need
                    self._lane_pages[lane].extend(pages)
            self._note_pages_peak()
            b_prime = 1
            while b_prime < len(rows):
                b_prime *= 2
            p_bucket = 1
            p_need = max(len(self._lane_pages[lane])
                         for lane, _, _ in rows)
            while p_bucket < p_need:
                p_bucket *= 2
            sel = np.zeros((b_prime,), dtype=np.int32)
            scatter_idx = np.full((b_prime,), self._lanes,
                                  dtype=np.int32)
            pos = np.zeros((b_prime,), dtype=np.int32)
            limit = np.zeros((b_prime,), dtype=np.int32)
            eos_stop = np.zeros((b_prime,), dtype=bool)
            tables = np.zeros((b_prime, p_bucket), dtype=np.int32)
            payload = {}
            for row, (lane, req, steps) in enumerate(rows):
                sel[row] = lane
                scatter_idx[row] = lane
                pos[row] = self._lane_pos[lane]
                limit[row] = steps
                eos_stop[row] = not req.ignore_eos
                tables[row, :len(self._lane_pages[lane])] = \
                    self._lane_pages[lane]
                payload[lane] = (req, steps, row)
            params = self._params
            tokens_dev = self._tokens_dev
            done_dev = self._done_dev
            pool = self._pool_dev
        busy_t0 = time.monotonic_ns()
        tok_c, done_c = self._gather_lanes(tokens_dev, done_dev,
                                           jnp.asarray(sel))
        emitted, tok_o, done_o, pool = self._paged_decode(
            params, tok_c, jnp.asarray(pos), jnp.asarray(limit),
            jnp.asarray(eos_stop), done_c, jnp.asarray(tables), pool)
        tokens_dev, done_dev = self._scatter_lanes(
            tokens_dev, done_dev, jnp.asarray(scatter_idx), tok_o,
            done_o)
        self._record_busy(busy_t0)
        fut = self._fetch_pool.submit(np.asarray, emitted)
        with self._sched_cv:
            if self._sched_stop or self._gen != gen:
                # A concurrent _crash/unload reset the pipeline while
                # this dispatch ran unlocked (see the dense loop's
                # comment) — drop the stale record.
                return True
            self._pool_dev = pool
            self._tokens_dev = tokens_dev
            self._done_dev = done_dev
            for lane, (req, steps, row) in payload.items():
                self._lane_pos[lane] += steps
                self._lane_steps_left[lane] -= steps
            self._inflight += 1
            self._delivery_queue.append(("chunk", fut, payload))
            self._sched_cv.notify_all()
        return True

    def kv_stats(self) -> Optional[dict]:
        """Paged-cache accounting for /metrics (``tpu_kv_*`` /
        ``tpu_prefill_*`` families) and the bench/smoke leak gates.
        None on the dense arm."""
        if not self._paged:
            return None
        with self._sched_cv:
            if self._pool is None:
                snap = {"pages_total": self._num_pages, "pages_used": 0,
                        "pages_cached": 0, "pages_free": self._num_pages,
                        "pages_reserved": 0}
            else:
                snap = self._pool.snapshot()
            snap.update(self._kv_counters)
            return snap

    def _collect_riders(self):
        """Every request the pipeline still owes tokens to: active
        lanes, queued joins, admitted-but-not-yet-active joins (paged
        batched prefills in dispatch + chunked prefill jobs), and
        requests riding undelivered records. Caller holds _sched_cv."""
        riders = (list(self._active.values()) + self._join_queue
                  + list(self._joining))
        for _, _, payload in self._delivery_queue:
            if isinstance(payload, dict):
                riders.extend(entry[0] for entry in payload.values())
            else:
                riders.extend(req for _, req in payload)
        return riders

    def _crash(self, message: str, gen: int):
        """Fails every rider and resets the pipeline so a later
        request restarts it cleanly (the donated cache may already be
        consumed; leaked lanes would leave a restart spinning)."""
        with self._sched_cv:
            if self._gen != gen:  # another thread already reset
                return
            self._gen += 1
            for req in self._collect_riders():
                req.fail(message)
            self._active.clear()
            self._join_queue.clear()
            self._delivery_queue.clear()
            self._inflight = 0
            self._free_lanes = list(range(self._lanes))
            self._lane_pos = [0] * self._lanes
            self._tokens_dev = None
            self._batched_cache = None
            self._reset_paged_state()
            self._sched_thread = None
            self._delivery_thread = None
            self._sched_cv.notify_all()

    def _attribute_thread(self):
        """Sticky compile attribution for a model-owned worker thread:
        XLA compiles on the decode scheduler / background prefill-
        compile threads land on this model, not `unattributed`."""
        try:
            from client_tpu.server import devstats

            devstats.get().set_thread_model(self.name)
        except Exception:  # noqa: BLE001 — attribution is advisory
            pass

    def _device_ledger(self):
        """The process-wide HBM ledger (None when the devstats layer
        is unavailable — accounting must never block serving)."""
        try:
            from client_tpu.server import devstats

            return devstats.get().ledger
        except Exception:  # noqa: BLE001
            return None

    def _hbm_allocator(self):
        """The process-wide HBM allocator (None when the server layer
        is unavailable — accounting must never block serving)."""
        try:
            from client_tpu.server import hbm

            return hbm.get()
        except Exception:  # noqa: BLE001
            return None

    def _kv_device_keys(self) -> list:
        """The allocator device keys the KV slab books against: [None]
        (= first device) unsharded; one key per slice member when the
        model is mesh-sharded, so each device's budget carries exactly
        its sub-pool."""
        if self._mesh is None:
            return [None]
        try:
            return ["%s-%d" % (d.platform.upper(), d.id)
                    for d in self._mesh.devices.flat]
        except Exception:  # noqa: BLE001 — exotic mesh stand-ins
            return [None]

    def _release_kv_lease(self) -> None:
        """Returns the slab's bytes to the allocator (and any legacy
        direct ledger row). Lock-only — safe under _sched_cv."""
        allocator = self._hbm_allocator()
        leases, self._kv_leases = self._kv_leases, []
        if allocator is not None:
            for lease in leases:
                allocator.release(lease)
        ledger = self._device_ledger()
        if ledger is not None:
            ledger.release(self._kv_ledger_row)
        self._kv_ledger_row = None

    def _ensure_page_pool(self) -> None:
        """Carves the KV slab from the HBM allocator BEFORE entering
        the scheduler's condition variable (the deferred PR-13
        follow-up): budgeted admission may evict cold paged weights —
        device<->host transfers that must never run under _sched_cv —
        and a slab that loses even after eviction sheds with the
        allocator's honest RESOURCE_EXHAUSTED deferral instead of an
        opaque OOM. The reservation invariant is untouched: _PagePool
        still carves its pages out of this one slab."""
        if self._pool_dev is not None:
            return
        self._pool_admission.acquire()
        try:
            if self._pool_dev is not None or self._sched_stop:
                return
            allocator = self._hbm_allocator()
            leases: list = []
            committed = False
            try:
                if allocator is not None:
                    total = page_pool_nbytes(self.cfg, self._num_pages,
                                             self._page_size)
                    keys = self._kv_device_keys()
                    # Mesh-sharded: one lease per slice member for its
                    # sub-pool share, admitted under THAT device's
                    # arbitration mutex — no device carries another's
                    # pages in the budget.
                    share = -(-total // len(keys))
                    for device_key in keys:
                        leases.append(allocator.lease(
                            self.name,
                            "kv_pages" if device_key is None
                            else "kv_pages:%s" % device_key,
                            share, device_key=device_key,
                            reason="kv_pool"))
                pool_dev = init_page_pool(self.cfg, self._num_pages,
                                          self._page_size,
                                          mesh=self._mesh)
                with self._sched_cv:
                    self._pool_dev = pool_dev
                    self._kv_leases = leases
                committed = True
            finally:
                if not committed and allocator is not None:
                    for lease in leases:
                        allocator.release(lease)
        finally:
            self._pool_admission.release()

    def _record_busy(self, t0_ns: int) -> None:
        """Feeds the device busy-time counter with one dispatch's wall
        time. The scheduler serializes dispatches, so on the blocking
        CPU sim wall ~= device occupancy; on async accelerator
        backends the jit call returns at enqueue and this bounds
        device time from below — duty cycle under pure LLM load is
        then an underestimate, never a zero."""
        try:
            from client_tpu.server import devstats

            devstats.get().record_busy(
                None, time.monotonic_ns() - t0_ns)
        except Exception:  # noqa: BLE001 — accounting is advisory
            pass

    def _reset_paged_state(self):
        """Caller holds _sched_cv. A crash rebuilds the page pool from
        scratch — the generation bump must not leak pages (the old
        pool's host accounting and device arrays are dropped wholesale,
        so accounting restarts at zero by construction). The ledger
        row goes with the device arrays: a crashed pool must not keep
        claiming HBM in the cross-model accounting."""
        self._prefill_jobs.clear()
        self._joining.clear()
        self._pool = None
        self._release_kv_lease()
        self._pool_dev = None
        self._done_dev = None
        self._lane_pages = [[] for _ in range(self._lanes)]
        self._lane_reserved = [0] * self._lanes
        self._lane_steps_left = [0] * self._lanes

    def unload(self) -> None:
        self._release_kv_lease()
        with self._sched_cv:
            self._sched_stop = True
            for req in self._collect_riders():
                req.fail("model unloaded")
            self._active.clear()
            self._join_queue.clear()
            self._delivery_queue.clear()
            self._prefill_jobs.clear()
            self._joining.clear()
            self._inflight = 0
            self._sched_cv.notify_all()
        if self._sched_thread is not None:
            self._sched_thread.join(timeout=10)
        if self._delivery_thread is not None:
            self._delivery_thread.join(timeout=10)
        if self._fetch_pool is not None:
            self._fetch_pool.shutdown(wait=False)

    def _generate(self, inputs, parameters):
        text = inputs["text_input"].reshape(-1)[0]
        if isinstance(text, bytes):
            text = text.decode("utf-8", errors="replace")
        else:
            text = str(text)
        max_tokens = int(
            inputs.get("max_tokens", np.array([32])).reshape(-1)[0]
        )
        max_tokens = max(1, min(max_tokens, self.cfg.max_seq - 2))
        ignore_eos = bool(
            inputs.get("ignore_eos", np.array([False])).reshape(-1)[0]
        )
        prompt = self._tokenizer.encode(text)
        prompt = prompt[-(self.cfg.max_seq - max_tokens - 1):]
        request = _GenRequest(prompt, max_tokens, ignore_eos)
        if self._paged:
            request.page_hashes = prefix_page_hashes(prompt,
                                                     self._page_size)
        timeout_us = self._queue_timeout_s * 1e6
        raw_timeout = (parameters or {}).get("timeout")
        if raw_timeout is not None:
            # PR-2 queue-policy semantics: 0 (or non-numeric) means
            # "no per-request override", keeping the model default —
            # matching the dynamic batcher's `timeout` coercion.
            try:
                value = float(raw_timeout)
            except (TypeError, ValueError):
                value = 0.0
            if value > 0:
                timeout_us = value
        if self._paged and self._pool_dev is None:
            # Budgeted slab admission runs before the scheduler cv
            # (it can evict, i.e. run device transfers) — see
            # _ensure_page_pool.
            self._ensure_page_pool()
        with self._sched_cv:
            if self._sched_stop:
                raise InferenceServerException(
                    "model '%s' is unloaded" % self.name,
                    status="UNAVAILABLE")
            if self._paged:
                worst_pages = -(-min(len(prompt) + max_tokens - 1,
                                     self.cfg.max_seq)
                                // self._page_size)
                if worst_pages > self._num_pages:
                    # Larger than the whole pool: no amount of waiting
                    # admits it — reject immediately, not retryably.
                    raise InferenceServerException(
                        "model '%s': prompt + max_tokens needs %d KV "
                        "pages but the pool holds %d"
                        % (self.name, worst_pages, self._num_pages),
                        status="INVALID_ARGUMENT")
                # Page-exhaustion admission control: past the join
                # watermark, shed at the door with an honest
                # Retry-After estimating page-free time instead of
                # queueing the request to die on its deadline.
                if len(self._join_queue) >= self._join_watermark:
                    self._kv_counters["shed_total"] += 1
                    raise retryable_error(
                        "model '%s': KV page pool saturated "
                        "(%d joins already waiting for pages)"
                        % (self.name, len(self._join_queue)),
                        status="RESOURCE_EXHAUSTED",
                        retry_after_s=self._page_wait_estimate_locked())
                request.enqueue_ns = time.monotonic_ns()
                request.deadline_ns = (request.enqueue_ns
                                       + int(timeout_us * 1000))
                if self._pool is None:
                    self._pool = _PagePool(self._num_pages,
                                           self._page_size)
                if self._pool_dev is None:
                    # Crash-rebuild fallback: a scheduler reset
                    # cleared the slab after _ensure_page_pool ran.
                    # Best-effort leases only — no eviction (and no
                    # device<->host transfers) under the cv.
                    self._pool_dev = init_page_pool(
                        self.cfg, self._num_pages, self._page_size,
                        mesh=self._mesh)
                    allocator = self._hbm_allocator()
                    if allocator is not None:
                        total = sum(int(k.nbytes) + int(v.nbytes)
                                    for k, v in self._pool_dev)
                        keys = self._kv_device_keys()
                        share = -(-total // len(keys))
                        self._kv_leases = [
                            allocator.lease(
                                self.name,
                                "kv_pages" if key is None
                                else "kv_pages:%s" % key,
                                share, device_key=key,
                                best_effort=True)
                            for key in keys]
                if self._done_dev is None:
                    self._done_dev = jnp.zeros((self._lanes,),
                                               dtype=bool)
            elif self._batched_cache is None:
                self._batched_cache = init_cache(self.cfg, self._lanes)
            if self._tokens_dev is None:
                self._tokens_dev = jnp.full(
                    (self._lanes,), PAD, dtype=jnp.int32)
            self._join_queue.append(request)
            self._sched_cv.notify_all()
        # AFTER enqueuing: a scheduler that crashed between the
        # liveness check and the append would otherwise leave the
        # request stranded — this restart sees it in the queue.
        self._ensure_scheduler()
        cancel = (parameters or {}).get("cancel_token")
        handle = None
        if cancel is not None:
            # Explicit cancellation (wire cancel, hedge loser, chaos
            # abandon) between decode chunks: mark the lane for reap,
            # wake the consumer with the end sentinel, and poke the
            # scheduler so pages/reservations free at the NEXT chunk
            # boundary instead of after the full decode budget.
            def _reap_lane():
                request.cancelled = True
                request.queue.put(None)
                with self._sched_cv:
                    self._sched_cv.notify_all()
            handle = cancel.on_cancel(_reap_lane)
        try:
            while True:
                token = request.queue.get()
                if token is None:
                    break
                yield token
        finally:
            if handle is not None:
                cancel.remove_callback(handle)
            # Consumer gone (client disconnect closes the generator):
            # let the scheduler reclaim the lane at the next chunk.
            request.cancelled = True
        if request.error is not None:
            raise InferenceServerException(request.error,
                                           status=request.error_status)

    def infer_stream(self, inputs, parameters=None
                     ) -> Iterator[Dict[str, np.ndarray]]:
        for token in self._generate(inputs, parameters or {}):
            piece = self._tokenizer.decode([token])
            yield {
                "text_output": np.array([piece.encode()], dtype=np.object_)
            }

    def infer(self, inputs, parameters=None) -> Dict[str, np.ndarray]:
        tokens = list(self._generate(inputs, parameters or {}))
        text = self._tokenizer.decode(tokens)
        return {"text_output": np.array([text.encode()], dtype=np.object_)}

    def flops_per_token(self) -> float:
        """Decode FLOPs per generated token ≈ 2 * parameter count
        (matmul-dominated; KV-cache attention reads are minor at tiny
        sequence lengths) — the serving-MFU numerator."""
        import jax as _jax

        n_params = sum(int(x.size) for x in _jax.tree_util.tree_leaves(
            self._params))
        return 2.0 * n_params

    def warmup(self) -> None:
        # Prime the prefill shapes concurrent serving hits (power-of
        # -two join batches x the two common prompt buckets) so no
        # multi-second XLA compile lands mid-stream; the persistent
        # compilation cache makes repeat warmups near-free.
        pow2s = [1]
        while pow2s[-1] < self._lanes:  # ceiling pow2 covers any group
            pow2s.append(pow2s[-1] * 2)
        for b in pow2s:
            for bucket in sorted({min(16, self.cfg.max_seq),
                                  min(64, self.cfg.max_seq)}):
                if (b, bucket) not in self._prefill_exec:
                    try:
                        self._compile_prefill(b, bucket)
                    except Exception:  # noqa: BLE001 — warmup best-effort
                        pass
        # The join path's small shape-dependent kernels (cache row
        # insert per prefill batch, token scatter per join-group size)
        # also compile per shape — prime them too, or the first
        # concurrent join round stalls every stream for the compile.
        if self._paged:
            self._warmup_paged(pow2s)
        else:
            try:
                for b in pow2s:
                    scratch = self._lane_insert_row(
                        init_cache(self.cfg, self._lanes),
                        init_cache(self.cfg, b), np.int32(0),
                        np.int32(0))
                    del scratch
                toks = jnp.full((self._lanes,), PAD, dtype=jnp.int32)
                for g in range(1, self._lanes + 1):
                    toks = self._set_lane_tokens(
                        toks, jnp.arange(g, dtype=jnp.int32),
                        jnp.full((g,), PAD, dtype=jnp.int32))
                del toks
            except Exception:  # noqa: BLE001 — warmup best-effort
                pass
        list(self.infer_stream({
            "text_input": np.array([b"hi"], dtype=np.object_),
            "max_tokens": np.array([2], dtype=np.int32),
        }))

    def _warmup_paged(self, pow2s):
        """Primes the paged kernels' common shape buckets on a
        throwaway pool: decode chunks per (compact batch, table
        width), the prefill chunk kernel, the pack kernel, and the
        lane gather/scatter helpers — an inline XLA compile
        mid-serving would stall every active token stream."""
        try:
            ps = self._page_size
            p_buckets = []
            p = 1
            while p <= self._pages_per_seq:
                p_buckets.append(p)
                p *= 2
            p_buckets = p_buckets[:4]  # short-context buckets dominate
            pool = init_page_pool(self.cfg, self._num_pages, ps)
            for b_prime in {1, self._lanes}:
                for p_bucket in p_buckets:
                    zeros = np.zeros((b_prime,), dtype=np.int32)
                    _, _, _, pool = self._paged_decode(
                        self._params, jnp.asarray(zeros),
                        jnp.asarray(zeros), jnp.asarray(zeros),
                        jnp.zeros((b_prime,), dtype=bool),
                        jnp.zeros((b_prime,), dtype=bool),
                        jnp.zeros((b_prime, p_bucket), dtype=jnp.int32),
                        pool)
            for p_bucket in p_buckets:
                sentinel = np.full((self._prefill_chunk,),
                                   self._num_pages * ps,
                                   dtype=np.int32)
                _, pool = self._paged_prefill(
                    self._params,
                    jnp.full((1, self._prefill_chunk), PAD,
                             dtype=jnp.int32),
                    jnp.arange(self._prefill_chunk, dtype=jnp.int32),
                    jnp.asarray(sentinel), np.int32(0),
                    jnp.zeros((1, p_bucket), dtype=jnp.int32), pool)
            for b in pow2s:
                for bucket in sorted({min(16, self.cfg.max_seq),
                                      min(64, self.cfg.max_seq)}):
                    pool = self._pack_pages(
                        pool, init_cache(self.cfg, b, length=bucket),
                        jnp.full((b * bucket,), self._num_pages * ps,
                                 dtype=jnp.int32))
            toks = jnp.full((self._lanes,), PAD, dtype=jnp.int32)
            done = jnp.zeros((self._lanes,), dtype=bool)
            for b_prime in {1, self._lanes}:
                idx = jnp.zeros((b_prime,), dtype=jnp.int32)
                tok_c, done_c = self._gather_lanes(toks, done, idx)
                toks, done = self._scatter_lanes(
                    toks, done,
                    jnp.full((b_prime,), self._lanes, dtype=jnp.int32),
                    tok_c, done_c)
            for g in {1, min(2, self._lanes), self._lanes}:
                toks, done = self._join_lanes(
                    toks, done, jnp.zeros((g,), dtype=jnp.int32),
                    jnp.full((g,), PAD, dtype=jnp.int32))
            del pool, toks, done
        except Exception:  # noqa: BLE001 — warmup best-effort
            pass
