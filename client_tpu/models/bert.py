"""BERT-base sequence classification (BASELINE config #3: dynamic
batching + variable sequence length).

Variable-length inputs are bucketed to a small set of padded lengths
so XLA compiles a handful of static shapes instead of one per length
(the TPU answer to dynamic shapes)."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.protocol import model_config_pb2 as mc
from client_tpu.server.model import ServedModel, TensorSpec


@dataclasses.dataclass
class BertConfig:
    vocab: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq: int = 512
    num_labels: int = 2
    dtype: str = "bfloat16"


def init_params(key, cfg: BertConfig) -> Dict:
    dtype = jnp.dtype(cfg.dtype)
    ks = jax.random.split(key, 4 + cfg.n_layers)

    def norm(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, dtype=jnp.float32)
                * scale).astype(dtype)

    params = {
        "word_embed": norm(ks[0], (cfg.vocab, cfg.d_model)),
        "pos_embed": norm(ks[1], (cfg.max_seq, cfg.d_model)),
        "embed_norm": {"scale": jnp.ones((cfg.d_model,), dtype=dtype),
                       "bias": jnp.zeros((cfg.d_model,), dtype=dtype)},
        "layers": [],
        "pooler": norm(ks[2], (cfg.d_model, cfg.d_model)),
        "classifier": norm(ks[3], (cfg.d_model, cfg.num_labels)),
    }
    head_dim = cfg.d_model // cfg.n_heads
    for i in range(cfg.n_layers):
        lk = jax.random.split(ks[4 + i], 6)
        params["layers"].append({
            "wq": norm(lk[0], (cfg.d_model, cfg.n_heads, head_dim)),
            "wk": norm(lk[1], (cfg.d_model, cfg.n_heads, head_dim)),
            "wv": norm(lk[2], (cfg.d_model, cfg.n_heads, head_dim)),
            "wo": norm(lk[3], (cfg.n_heads, head_dim, cfg.d_model)),
            "norm1": {"scale": jnp.ones((cfg.d_model,), dtype=dtype),
                      "bias": jnp.zeros((cfg.d_model,), dtype=dtype)},
            "w_up": norm(lk[4], (cfg.d_model, cfg.d_ff)),
            "w_down": norm(lk[5], (cfg.d_ff, cfg.d_model)),
            "norm2": {"scale": jnp.ones((cfg.d_model,), dtype=dtype),
                      "bias": jnp.zeros((cfg.d_model,), dtype=dtype)},
        })
    return params


def _layer_norm(x, p, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = x32.var(axis=-1, keepdims=True)
    return (((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
            * p["scale"] + p["bias"])


def forward(params, input_ids, attention_mask, cfg: BertConfig):
    """input_ids/attention_mask [B,S] -> logits [B,num_labels]."""
    b, s = input_ids.shape
    x = params["word_embed"][input_ids] + params["pos_embed"][None, :s]
    x = _layer_norm(x, params["embed_norm"])
    mask = attention_mask.astype(bool)[:, None, None, :]  # [B,1,1,S]
    head_dim = cfg.d_model // cfg.n_heads
    for layer in params["layers"]:
        q = jnp.einsum("bsd,dhk->bshk", x, layer["wq"])
        k = jnp.einsum("bsd,dhk->bshk", x, layer["wk"])
        v = jnp.einsum("bsd,dhk->bshk", x, layer["wv"])
        logits = jnp.einsum("bshk,bthk->bhst", q, k).astype(jnp.float32)
        logits = logits / np.sqrt(head_dim)
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        ctx = jnp.einsum("bhst,bthk->bshk", probs, v)
        x = _layer_norm(
            x + jnp.einsum("bshk,hkd->bsd", ctx, layer["wo"]),
            layer["norm1"],
        )
        h = jax.nn.gelu(x @ layer["w_up"])
        x = _layer_norm(x + h @ layer["w_down"], layer["norm2"])
    pooled = jnp.tanh(x[:, 0] @ params["pooler"])
    return (pooled @ params["classifier"]).astype(jnp.float32)


_BUCKETS = (32, 64, 128, 256, 512)


def _bucket_length(s: int, max_seq: int) -> int:
    """Smallest padding bucket >= s, doubling past the static list and
    capped at max_seq (inputs beyond max_seq get truncated)."""
    for bucket in _BUCKETS:
        if s <= bucket:
            return min(bucket, max_seq)
    bucket = _BUCKETS[-1]
    while bucket < s and bucket < max_seq:
        bucket *= 2
    return min(bucket, max_seq)


class BertModel(ServedModel):
    """Inputs input_ids/attention_mask INT32 [-1]; output logits
    [num_labels]. Declares dynamic batching in its config."""

    platform = "jax"
    # Fuse ceiling 64: with the batcher's async output fetch the
    # served-request cadence is relay-latency bound (~65 ms/round
    # trip), so throughput scales with how many concurrent requests
    # fuse into one MXU call — bert-base batch 64 is still ~4 ms of
    # device compute, far below the fetch it hides behind. The 4 ms
    # queue window spans a whole response burst (requests re-arrive in
    # waves at this latency), growing the average fused batch from ~7
    # to ~32 at 64 clients; it adds 4 ms to a ~130 ms round trip.
    max_batch_size = 64
    dynamic_batching = True
    preferred_batch_sizes = [8, 16, 32, 64]
    max_queue_delay_us = 4000
    # Opt into the adaptive gather window: under the bench's c64
    # burst the inter-arrival EMA stretches the window toward
    # delay_max so whole preferred batches form (r05 fused only ~11
    # of 64); the idle-gap cutoff keeps sparse/stalled traffic at the
    # 4 ms floor, so the ceiling is only ever paid when arrivals can
    # actually fill a batch.
    delay_min_us = 4000
    delay_max_us = 64000
    # Queue policy: bound pending work at 16x the fuse ceiling — far
    # above the closed-loop bench's c64 (which must never see a
    # reject) but finite, so open-loop overload sheds with 503/
    # UNAVAILABLE instead of growing the queue without bound; queued
    # requests nobody will wait >2s for expire before touching the
    # device.
    max_queue_size = 1024
    default_queue_policy_timeout_us = 2_000_000

    def __init__(self, name: str = "bert_base", cfg: Optional[BertConfig]
                 = None, seed: int = 0):
        super().__init__()
        self.name = name
        self.cfg = cfg or BertConfig()
        self.inputs = [
            TensorSpec("input_ids", "INT32", [-1]),
            TensorSpec("attention_mask", "INT32", [-1], optional=True),
        ]
        self.outputs = [TensorSpec("logits", "FP32", [self.cfg.num_labels])]
        self._params = init_params(jax.random.PRNGKey(seed), self.cfg)
        cfg_static = self.cfg
        self._fn = jax.jit(
            lambda p, ids, mask: forward(p, ids, mask, cfg_static)
        )

    def infer(self, inputs, parameters=None):
        ids = np.asarray(inputs["input_ids"])
        if ids.ndim == 1:
            ids = ids[None]
        mask = inputs.get("attention_mask")
        mask = (
            np.asarray(mask) if mask is not None
            else np.ones_like(ids)
        )
        if mask.ndim == 1:
            mask = mask[None]
        s = ids.shape[1]
        # pad to a bucket (capped at max_seq) so XLA reuses compilations
        bucket = _bucket_length(s, self.cfg.max_seq)
        if s > bucket:
            ids = ids[:, :bucket]
            mask = mask[:, :bucket]
        elif s < bucket:
            pad = ((0, 0), (0, bucket - s))
            ids = np.pad(ids, pad)
            mask = np.pad(mask, pad)
        logits = self._fn(self._params, jnp.asarray(ids), jnp.asarray(mask))
        return {"logits": logits}

    def warmup(self) -> None:
        # Compile the fused-batch grid at the first seq bucket: the
        # dynamic batcher pads to preferred_batch_sizes, and a
        # multi-second XLA compile landing inside a measurement window
        # (instead of here) shows up as an 8-second p99. Other seq
        # buckets still compile on first use — the persistent
        # compilation cache absorbs repeats.
        seq = min(_BUCKETS[0], self.cfg.max_seq)
        for batch in (1,) + tuple(self.preferred_batch_sizes):
            ids = jnp.zeros((batch, seq), dtype=jnp.int32)
            jax.block_until_ready(self._fn(self._params, ids,
                                           jnp.ones_like(ids)))

    def flops_estimate(self, batch: int, seq: int = 0):
        # Encoder forward at padded length S, per layer:
        #   QKV+output projections 8*S*d^2, FFN 2*2*S*d*d_ff,
        #   attention scores+context 4*S^2*d.
        cfg = self.cfg
        s = seq or _bucket_length(1, cfg.max_seq)
        per_layer = (8 * s * cfg.d_model ** 2
                     + 4 * s * cfg.d_model * cfg.d_ff
                     + 4 * s * s * cfg.d_model)
        return float(batch * cfg.n_layers * per_layer)
