"""The `simple` add/sub model: OUTPUT0 = INPUT0 + INPUT1,
OUTPUT1 = INPUT0 - INPUT1 — the protocol-conformance and latency-floor
workhorse (reference examples' `simple` model; BASELINE config #1).

Placement: defaults to the host CPU backend — for a 64-byte tensor the
accelerator round trip is pure loss (on this image the TPU relay's
device-to-host hop alone is ~20 ms). Pass ``device="tpu"`` to pin it
on the accelerator, which is the right choice when I/O rides TPU
shared-memory regions and never leaves HBM.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.server.model import ServedModel, TensorSpec
from client_tpu.utils import triton_to_np_dtype


class AddSub(ServedModel):
    """Element-wise add/sub over two same-shape inputs, one fused XLA
    kernel. Device-resident inputs (TPU shm regions) are consumed in
    place with no host round-trip."""

    platform = "jax"

    def __init__(self, name: str = "add_sub", datatype: str = "INT32",
                 shape=(16,), device: str = "cpu"):
        super().__init__()
        self.name = name
        self._datatype = datatype
        self._shape = list(shape)
        self._device_kind = device
        self.inputs = [
            TensorSpec("INPUT0", datatype, self._shape),
            TensorSpec("INPUT1", datatype, self._shape),
        ]
        self.outputs = [
            TensorSpec("OUTPUT0", datatype, self._shape),
            TensorSpec("OUTPUT1", datatype, self._shape),
        ]
        self._fn = jax.jit(lambda a, b: (a + b, a - b))
        self._device = None
        if device == "cpu":
            self._device = jax.devices("cpu")[0]

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        a, b = inputs["INPUT0"], inputs["INPUT1"]
        if (
            self._device is not None
            and isinstance(a, np.ndarray)
            and isinstance(b, np.ndarray)
        ):
            # Host tensors on a host-placed model: plain numpy is the
            # fastest "kernel" there is for 16 elements.
            return {"OUTPUT0": a + b, "OUTPUT1": a - b}
        out0, out1 = self._fn(a, b)
        return {"OUTPUT0": out0, "OUTPUT1": out1}

    def warmup(self) -> None:
        np_dtype = triton_to_np_dtype(self._datatype)
        if self._device is not None:
            with jax.default_device(self._device):
                zero = jnp.zeros(self._shape, dtype=np_dtype)
                jax.block_until_ready(self._fn(zero, zero))
        else:
            zero = jnp.zeros(self._shape, dtype=np_dtype)
            jax.block_until_ready(self._fn(zero, zero))


class MultiOutLarge(ServedModel):
    """Relay-fetch testbed: a tiny input fans out to ``out_count``
    multi-MiB outputs (default 4 x 4 MiB fp32), so the device->host
    output relay — not compute — dominates the request. The
    ``fetch_bench`` / ``fetch_bench_legacy`` pair A/Bs the overlapped
    fetch subsystem (client_tpu.server.fetch) against the serial
    blocking np.asarray baseline on otherwise identical models
    (tools/fetch_smoke.py and the bench relay_fetch stage).

    Dynamic batching with preferred size 4 keeps single requests off
    the batcher's passthrough shortcut (batch 1 pads to 4), so every
    execution exercises the fused-output fetch path the A/B measures.
    Placement follows the default device — the accelerator when one is
    present, which is where the relay tax is real."""

    platform = "jax"

    def __init__(self, name: str = "fetch_bench", out_count: int = 4,
                 elements: int = 1 << 20, overlapped: bool = True):
        super().__init__()
        self.name = name
        self.max_batch_size = 4
        self.dynamic_batching = True
        self.preferred_batch_sizes = [4]
        self.max_queue_delay_us = 2000
        self.overlapped_fetch = overlapped
        self._out_count = out_count
        self._elements = elements
        self.inputs = [TensorSpec("INPUT0", "FP32", [16])]
        self.outputs = [
            TensorSpec("OUTPUT%d" % i, "FP32", [elements])
            for i in range(out_count)
        ]

        def produce(a):
            base = jnp.sum(a, axis=-1, keepdims=True)  # (batch, 1)
            ramp = jnp.arange(elements, dtype=jnp.float32)
            return tuple(base + ramp * float(i + 1)
                         for i in range(out_count))

        self._fn = jax.jit(produce)

    def infer(self, inputs: Dict[str, np.ndarray],
              parameters: Optional[dict] = None) -> Dict[str, np.ndarray]:
        outs = self._fn(jnp.asarray(inputs["INPUT0"], dtype=jnp.float32))
        return {"OUTPUT%d" % i: out for i, out in enumerate(outs)}

    def warmup(self) -> None:
        zero = jnp.zeros((self.max_batch_size, 16), dtype=jnp.float32)
        jax.block_until_ready(self._fn(zero))
