"""ResNet-50 (NHWC, bf16) — the north-star benchmark model
(BASELINE config #2: image classification with TPU shared-memory I/O).

Inference-mode batch norm folded into scale/bias; convs via
lax.conv_general_dilated in NHWC which XLA maps straight onto the MXU.
Weights are randomly initialized — the benchmark measures the serving
path, not accuracy.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from client_tpu.server.model import ServedModel, TensorSpec

STAGES = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}


@dataclasses.dataclass
class ResNetConfig:
    depth: int = 50
    num_classes: int = 1000
    width: int = 64
    dtype: str = "bfloat16"


def _conv_kernel(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout), dtype=jnp.float32)
            * np.sqrt(2.0 / fan_in)).astype(dtype)


def _bn(c, dtype):
    return {
        "scale": jnp.ones((c,), dtype=dtype),
        "bias": jnp.zeros((c,), dtype=dtype),
    }


def init_params(key, cfg: ResNetConfig) -> Dict:
    dtype = jnp.dtype(cfg.dtype)
    keys = jax.random.split(key, 64)
    ki = iter(range(64))
    params = {
        "stem": {
            "conv": _conv_kernel(keys[next(ki)], 7, 7, 3, cfg.width, dtype),
            "bn": _bn(cfg.width, dtype),
        },
        "stages": [],
    }
    cin = cfg.width
    for stage_idx, blocks in enumerate(STAGES[cfg.depth]):
        cmid = cfg.width * (2 ** stage_idx)
        cout = cmid * 4
        stage = []
        for block_idx in range(blocks):
            key = jax.random.fold_in(keys[next(ki) % 64], block_idx)
            bk = jax.random.split(key, 4)
            block = {
                "conv1": _conv_kernel(bk[0], 1, 1, cin, cmid, dtype),
                "bn1": _bn(cmid, dtype),
                "conv2": _conv_kernel(bk[1], 3, 3, cmid, cmid, dtype),
                "bn2": _bn(cmid, dtype),
                "conv3": _conv_kernel(bk[2], 1, 1, cmid, cout, dtype),
                "bn3": _bn(cout, dtype),
            }
            if block_idx == 0:
                block["proj"] = _conv_kernel(bk[3], 1, 1, cin, cout, dtype)
                block["proj_bn"] = _bn(cout, dtype)
            stage.append(block)
            cin = cout
        params["stages"].append(stage)
    head_key = keys[next(ki) % 64]
    params["head"] = {
        "kernel": (jax.random.normal(head_key, (cin, cfg.num_classes),
                                     dtype=jnp.float32) * 0.01).astype(dtype),
        "bias": jnp.zeros((cfg.num_classes,), dtype=dtype),
    }
    return params


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _apply_bn(x, bn):
    return x * bn["scale"] + bn["bias"]


def _bottleneck(x, block, stride):
    shortcut = x
    y = jax.nn.relu(_apply_bn(_conv(x, block["conv1"]), block["bn1"]))
    y = jax.nn.relu(_apply_bn(_conv(y, block["conv2"], stride), block["bn2"]))
    y = _apply_bn(_conv(y, block["conv3"]), block["bn3"])
    if "proj" in block:
        shortcut = _apply_bn(_conv(x, block["proj"], stride),
                             block["proj_bn"])
    return jax.nn.relu(y + shortcut)


def forward(params, images, cfg: ResNetConfig):
    """images [B, 224, 224, 3] -> logits [B, num_classes]."""
    x = images.astype(jnp.dtype(cfg.dtype))
    x = jax.nn.relu(_apply_bn(_conv(x, params["stem"]["conv"], 2),
                              params["stem"]["bn"]))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for stage_idx, stage in enumerate(params["stages"]):
        for block_idx, block in enumerate(stage):
            stride = 2 if (stage_idx > 0 and block_idx == 0) else 1
            x = _bottleneck(x, block, stride)
    x = jnp.mean(x, axis=(1, 2))
    logits = x @ params["head"]["kernel"] + params["head"]["bias"]
    return logits.astype(jnp.float32)


class ResNetModel(ServedModel):
    """Input "INPUT" FP32 [224,224,3] (NHWC, batchable), output
    "OUTPUT" FP32 [num_classes] — the image_client parity surface."""

    platform = "jax"
    max_batch_size = 32
    # Fuse concurrent requests into MXU-friendly batches server-side.
    dynamic_batching = True
    # Two compile shapes only: 8 leaves a lone batch-8 request
    # unpadded; fused buckets pad to 32 (the MXU sweet spot).
    preferred_batch_sizes = [8, 32]
    # 2 ms gather window: long enough for a burst of concurrent
    # ensemble backbone steps (batch-1 each, arriving within ~1 ms of
    # each other) to fuse, negligible against the ~65 ms relay floor.
    max_queue_delay_us = 2000

    def __init__(self, name: str = "resnet50", cfg: Optional[ResNetConfig]
                 = None, seed: int = 0):
        super().__init__()
        self.name = name
        self.cfg = cfg or ResNetConfig()
        self.inputs = [TensorSpec("INPUT", "FP32", [224, 224, 3])]
        self.outputs = [TensorSpec("OUTPUT", "FP32",
                                   [self.cfg.num_classes])]
        self._params = init_params(jax.random.PRNGKey(seed), self.cfg)
        cfg_static = self.cfg
        self._fn = jax.jit(lambda p, x: forward(p, x, cfg_static))

    def infer(self, inputs, parameters=None):
        images = inputs["INPUT"]
        # Unbatched single image (host OR device array — a device-side
        # preprocess step hands over jax.Arrays): add the batch dim.
        if getattr(images, "ndim", 0) == 3:
            images = images[None]
        return {"OUTPUT": self._fn(self._params, images)}

    def warmup(self) -> None:
        # Compile the single-sample path plus the dynamic batcher's
        # preferred fused shapes ahead of traffic.
        for batch in [1] + list(self.preferred_batch_sizes):
            x = jnp.zeros((batch, 224, 224, 3), dtype=jnp.float32)
            jax.block_until_ready(self._fn(self._params, x))

    def flops_estimate(self, batch: int, seq: int = 0):
        # Standard resnet50 forward at 224x224: ~3.86 GMAC ≈ 7.7e9
        # FLOPs per image (the constant the bench's MFU has used since
        # r03 — kept with the model so every consumer agrees).
        return batch * 7.7e9
