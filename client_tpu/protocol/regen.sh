#!/bin/sh
# Regenerate the protobuf python modules (run from repo root).
set -e
cd "$(dirname "$0")/../.."
protoc -I. --python_out=. \
  client_tpu/protocol/model_config.proto \
  client_tpu/protocol/inference.proto \
  client_tpu/protocol/arena.proto \
  client_tpu/protocol/tensorflow_serving.proto \
  client_tpu/protocol/tensorflow_serving_apis.proto
