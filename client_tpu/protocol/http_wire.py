"""KServe-v2 HTTP/REST wire format: JSON + binary tensor extension.

Shared by the HTTP client and the HTTP server front-end. The binary
tensor protocol appends raw little-endian tensor buffers after the
JSON header; ``Inference-Header-Content-Length`` tells the peer where
JSON ends (reference http_client.cc:2130-2247 and the v2 binary-data
extension).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from client_tpu._infer_common import (
    InferInput,
    InferRequestedOutput,
    build_request_parameters,
)
from client_tpu.protocol import inference_pb2 as pb
from client_tpu.utils import (
    InferenceServerException,
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    np_to_wire_dtype,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)

HEADER_LEN = "Inference-Header-Content-Length"


# -- body compression (client and server sides) ----------------------------

def compress_body(body: bytes, algorithm: str) -> bytes:
    """gzip / deflate body compression ("deflate" is the zlib format,
    per RFC 9110 §8.4.1)."""
    if algorithm == "gzip":
        import gzip

        return gzip.compress(body)
    if algorithm == "deflate":
        import zlib

        return zlib.compress(body)
    raise InferenceServerException(
        "unsupported compression algorithm '%s' (gzip or deflate)"
        % algorithm
    )


def decompress_body(body: bytes, content_encoding: Optional[str]) -> bytes:
    """Undoes Content-Encoding; identity/absent passes through."""
    if not content_encoding or content_encoding == "identity":
        return body
    if content_encoding == "gzip":
        import gzip

        return gzip.decompress(body)
    if content_encoding == "deflate":
        import zlib

        return zlib.decompress(body)
    raise InferenceServerException(
        "unsupported Content-Encoding '%s'" % content_encoding
    )


def _json_safe_param(value):
    if isinstance(value, (bool, int, float, str)):
        return value
    raise InferenceServerException(
        "unsupported parameter type %s" % type(value).__name__
    )


# -- request: client encode ------------------------------------------------


def encode_infer_request(
    inputs: Sequence[InferInput],
    outputs: Optional[Sequence[InferRequestedOutput]] = None,
    request_id: str = "",
    sequence_id: int = 0,
    sequence_start: bool = False,
    sequence_end: bool = False,
    priority: int = 0,
    timeout: Optional[int] = None,
    parameters: Optional[dict] = None,
) -> Tuple[bytes, Optional[int]]:
    """Build the POST body. Returns (body, json_header_length);
    header length is None when no input travels as binary (pure JSON
    body)."""
    header: Dict = {}
    if request_id:
        header["id"] = request_id
    params = build_request_parameters(
        sequence_id=sequence_id,
        sequence_start=sequence_start,
        sequence_end=sequence_end,
        priority=priority,
        timeout=timeout,
        parameters=parameters,
    )
    if params:
        header["parameters"] = {k: _json_safe_param(v) for k, v in params.items()}

    binary_blobs: List[bytes] = []
    header_inputs = []
    for infer_input in inputs:
        infer_input.validate()
        entry: Dict = {
            "name": infer_input.name(),
            "shape": infer_input.shape(),
            "datatype": infer_input.datatype(),
        }
        tensor_params = {
            k: _json_safe_param(v) for k, v in infer_input.parameters().items()
        }
        shm = infer_input.shared_memory()
        if shm is not None:
            region, byte_size, offset = shm
            tensor_params["shared_memory_region"] = region
            tensor_params["shared_memory_byte_size"] = byte_size
            if offset:
                tensor_params["shared_memory_offset"] = offset
        else:
            raw = infer_input.raw_data()
            if infer_input.binary_data():
                tensor_params["binary_data_size"] = len(raw)
                binary_blobs.append(raw)
            else:
                # JSON tensor data (binary_data=False): interoperable
                # with servers lacking the binary extension. BYTES
                # elements must be valid UTF-8 — a JSON string cannot
                # carry arbitrary binary, and a lossy re-encode would
                # silently corrupt the payload.
                if infer_input.datatype() == "BYTES":
                    try:
                        entry["data"] = [
                            b.decode("utf-8")
                            for b in deserialize_bytes_tensor(raw)
                        ]
                    except UnicodeDecodeError:
                        raise InferenceServerException(
                            "BYTES input '%s' holds non-UTF-8 bytes; "
                            "JSON tensor data cannot carry arbitrary "
                            "binary — use binary_data=True"
                            % infer_input.name(),
                            status="INVALID_ARGUMENT",
                        )
                else:
                    entry["data"] = _raw_to_json_data(
                        raw, infer_input.datatype())
        if tensor_params:
            entry["parameters"] = tensor_params
        header_inputs.append(entry)
    header["inputs"] = header_inputs

    if outputs:
        header_outputs = []
        for infer_output in outputs:
            entry = {"name": infer_output.name()}
            tensor_params = {
                k: _json_safe_param(v)
                for k, v in infer_output.parameters().items()
            }
            shm = infer_output.shared_memory()
            if shm is not None:
                region, byte_size, offset = shm
                tensor_params["shared_memory_region"] = region
                tensor_params["shared_memory_byte_size"] = byte_size
                if offset:
                    tensor_params["shared_memory_offset"] = offset
            else:
                tensor_params["binary_data"] = infer_output.binary_data()
            if infer_output.class_count():
                tensor_params["classification"] = infer_output.class_count()
            if tensor_params:
                entry["parameters"] = tensor_params
            header_outputs.append(entry)
        header["outputs"] = header_outputs

    json_bytes = json.dumps(header).encode()
    if binary_blobs:
        return json_bytes + b"".join(binary_blobs), len(json_bytes)
    return json_bytes, None


# -- request: server decode ------------------------------------------------


def decode_infer_request(
    body: bytes,
    model_name: str,
    model_version: str = "",
    header_length: Optional[int] = None,
) -> pb.ModelInferRequest:
    """Parse a POST /v2/models/<m>/infer body into the canonical
    ModelInferRequest proto (raw_input_contents carries tensor data)."""
    json_end = header_length if header_length is not None else len(body)
    try:
        header = json.loads(body[:json_end])
    except json.JSONDecodeError as e:
        raise InferenceServerException(
            "malformed inference request JSON: %s" % e, status="INVALID_ARGUMENT"
        )
    request = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version
    )
    request.id = header.get("id", "")
    for key, value in (header.get("parameters") or {}).items():
        _set_pb_param(request.parameters[key], value)

    binary_offset = json_end
    for entry in header.get("inputs", []):
        tensor = request.inputs.add()
        tensor.name = entry.get("name", "")
        tensor.datatype = entry.get("datatype", "")
        tensor.shape.extend(int(d) for d in entry.get("shape", []))
        params = entry.get("parameters") or {}
        binary_size = params.pop("binary_data_size", None)
        for key, value in params.items():
            _set_pb_param(tensor.parameters[key], value)
        if "shared_memory_region" in params:
            continue
        if binary_size is not None:
            end = binary_offset + int(binary_size)
            if end > len(body):
                raise InferenceServerException(
                    "binary input '%s' overruns request body" % tensor.name,
                    status="INVALID_ARGUMENT",
                )
            request.raw_input_contents.append(bytes(body[binary_offset:end]))
            binary_offset = end
        elif "data" in entry:
            request.raw_input_contents.append(
                _json_data_to_raw(entry["data"], tensor.datatype, tensor.name)
            )
        else:
            raise InferenceServerException(
                "input '%s' has no data" % tensor.name,
                status="INVALID_ARGUMENT",
            )

    for entry in header.get("outputs", []):
        tensor = request.outputs.add()
        tensor.name = entry.get("name", "")
        params = entry.get("parameters") or {}
        for key, value in params.items():
            _set_pb_param(tensor.parameters[key], value)
    return request


def _set_pb_param(param: pb.InferParameter, value):
    if isinstance(value, bool):
        param.bool_param = value
    elif isinstance(value, int):
        param.int64_param = value
    elif isinstance(value, float):
        param.double_param = value
    elif isinstance(value, str):
        param.string_param = value
    else:
        raise InferenceServerException(
            "unsupported parameter type %s" % type(value).__name__,
            status="INVALID_ARGUMENT",
        )


def _json_data_to_raw(data, datatype: str, name: str) -> bytes:
    """JSON "data" (nested or flat list) -> raw wire bytes."""
    if datatype == "BYTES":
        flat = np.array(data, dtype=np.object_).reshape(-1)
        coerced = np.array(
            [v.encode() if isinstance(v, str) else bytes(v) for v in flat],
            dtype=np.object_,
        )
        return serialize_byte_tensor(coerced).tobytes()
    np_dtype = triton_to_np_dtype(datatype)
    if np_dtype is None:
        raise InferenceServerException(
            "input '%s' has unknown datatype '%s'" % (name, datatype),
            status="INVALID_ARGUMENT",
        )
    if datatype == "BF16":
        arr = np.array(data, dtype=np.float32)
        return serialize_bf16_tensor(arr).tobytes()
    return np.ascontiguousarray(np.array(data, dtype=np_dtype)).tobytes()


# -- response: server encode ----------------------------------------------


def encode_infer_response(
    response: pb.ModelInferResponse,
    binary_prefs: Dict[str, bool],
    default_binary: bool = True,
) -> Tuple[bytes, Optional[int]]:
    """ModelInferResponse proto -> HTTP body. ``binary_prefs`` maps
    output name -> requested binary_data flag."""
    header: Dict = {
        "model_name": response.model_name,
        "model_version": response.model_version,
    }
    if response.id:
        header["id"] = response.id
    if response.parameters:
        header["parameters"] = {
            k: _pb_param_to_json(v) for k, v in response.parameters.items()
        }
    binary_blobs: List[bytes] = []
    header_outputs = []
    raw_idx = 0
    for tensor in response.outputs:
        entry: Dict = {
            "name": tensor.name,
            "datatype": tensor.datatype,
            "shape": [int(d) for d in tensor.shape],
        }
        params = {k: _pb_param_to_json(v) for k, v in tensor.parameters.items()}
        if "shared_memory_region" in tensor.parameters:
            entry["parameters"] = params
            header_outputs.append(entry)
            continue
        raw = response.raw_output_contents[raw_idx]
        raw_idx += 1
        use_binary = binary_prefs.get(tensor.name, default_binary)
        if use_binary:
            params["binary_data_size"] = len(raw)
            binary_blobs.append(raw)
            entry["parameters"] = params
        else:
            entry["data"] = _raw_to_json_data(raw, tensor.datatype)
            if params:
                entry["parameters"] = params
        header_outputs.append(entry)
    header["outputs"] = header_outputs
    json_bytes = json.dumps(header).encode()
    if binary_blobs:
        return json_bytes + b"".join(binary_blobs), len(json_bytes)
    return json_bytes, None


def _pb_param_to_json(param: pb.InferParameter):
    which = param.WhichOneof("parameter_choice")
    return getattr(param, which) if which else None


def _raw_to_json_data(raw: bytes, datatype: str):
    if datatype == "BYTES":
        arr = deserialize_bytes_tensor(raw)
        out = []
        for b in arr:
            try:
                out.append(b.decode("utf-8"))
            except UnicodeDecodeError:
                out.append(b.decode("latin-1"))
        return out
    if datatype == "BF16":
        return [float(x) for x in deserialize_bf16_tensor(raw)]
    arr = np.frombuffer(raw, dtype=triton_to_np_dtype(datatype))
    if datatype in ("FP16", "FP32", "FP64"):
        return [float(x) for x in arr]
    if datatype == "BOOL":
        return [bool(x) for x in arr]
    return [int(x) for x in arr]


# -- response: client decode ----------------------------------------------


class DecodedOutput:
    def __init__(self, name: str, datatype: str, shape, parameters: dict,
                 raw: Optional[bytes], json_data):
        self.name = name
        self.datatype = datatype
        self.shape = list(shape)
        self.parameters = parameters
        self.raw = raw
        self.json_data = json_data

    def as_numpy(self) -> Optional[np.ndarray]:
        if self.raw is not None:
            if self.datatype == "BYTES":
                return deserialize_bytes_tensor(self.raw).reshape(self.shape)
            if self.datatype == "BF16":
                return deserialize_bf16_tensor(self.raw).reshape(self.shape)
            return np.frombuffer(
                self.raw, dtype=triton_to_np_dtype(self.datatype)
            ).reshape(self.shape)
        if self.json_data is not None:
            if self.datatype == "BYTES":
                flat = np.array(
                    [
                        v.encode() if isinstance(v, str) else bytes(v)
                        for v in np.array(self.json_data, dtype=np.object_
                                          ).reshape(-1)
                    ],
                    dtype=np.object_,
                )
                return flat.reshape(self.shape)
            return np.array(
                self.json_data, dtype=triton_to_np_dtype(self.datatype)
            ).reshape(self.shape)
        return None  # output lives in shared memory


def decode_infer_response(
    body: bytes, header_length: Optional[int] = None
) -> Tuple[dict, Dict[str, DecodedOutput]]:
    """HTTP body -> (response header dict, outputs by name)."""
    json_end = header_length if header_length is not None else len(body)
    try:
        header = json.loads(body[:json_end])
    except json.JSONDecodeError as e:
        raise InferenceServerException(
            "malformed inference response JSON: %s" % e
        )
    outputs: Dict[str, DecodedOutput] = {}
    binary_offset = json_end
    for entry in header.get("outputs", []):
        params = entry.get("parameters") or {}
        raw = None
        if "binary_data_size" in params:
            size = int(params["binary_data_size"])
            raw = bytes(body[binary_offset : binary_offset + size])
            if len(raw) != size:
                raise InferenceServerException(
                    "binary output '%s' truncated" % entry.get("name")
                )
            binary_offset += size
        outputs[entry["name"]] = DecodedOutput(
            name=entry["name"],
            datatype=entry.get("datatype", ""),
            shape=entry.get("shape", []),
            parameters=params,
            raw=raw,
            json_data=entry.get("data"),
        )
    return header, outputs


# -- generate extension (LLM convenience API) ------------------------------
# JSON-by-input-name request bodies and flattened JSON responses,
# shared by the aiohttp front-end and the embedded REST dispatcher.


def build_generate_request(
    model_inputs, model_name: str, model_version: str, body: bytes
) -> pb.ModelInferRequest:
    """Generate-extension JSON body -> ModelInferRequest: fields that
    name a model input become tensors (scalars are wrapped), leftover
    scalar fields become request parameters."""
    try:
        doc = json.loads(body)
    except Exception as e:  # noqa: BLE001 — any parse failure is a 400
        raise InferenceServerException(
            "malformed generate request: %s" % e, status="INVALID_ARGUMENT"
        )
    if not isinstance(doc, dict):
        raise InferenceServerException(
            "generate request body must be a JSON object",
            status="INVALID_ARGUMENT",
        )
    request = pb.ModelInferRequest(
        model_name=model_name, model_version=model_version
    )
    for spec in model_inputs:
        if spec.name not in doc:
            continue
        value = doc.pop(spec.name)
        listed = value if isinstance(value, list) else [value]
        tensor = request.inputs.add()
        tensor.name = spec.name
        tensor.datatype = spec.datatype
        tensor.shape.extend([len(listed)])
        try:
            request.raw_input_contents.append(
                _json_data_to_raw(listed, spec.datatype, spec.name)
            )
        except (TypeError, ValueError, OverflowError) as e:
            raise InferenceServerException(
                "invalid value for input '%s': %s" % (spec.name, e),
                status="INVALID_ARGUMENT",
            )
    for key, value in doc.items():  # leftover fields -> parameters
        if isinstance(value, (bool, int, float, str)):
            _set_pb_param(request.parameters[key], value)
    return request


def generate_response_json(response: pb.ModelInferResponse) -> dict:
    """ModelInferResponse -> the generate extension's flat JSON doc
    (single-element tensors unwrap to scalars)."""
    doc = {
        "model_name": response.model_name,
        "model_version": response.model_version,
    }
    raw_idx = 0
    for tensor in response.outputs:
        if raw_idx >= len(response.raw_output_contents):
            continue
        data = _raw_to_json_data(
            response.raw_output_contents[raw_idx], tensor.datatype
        )
        raw_idx += 1
        doc[tensor.name] = data[0] if len(data) == 1 else data
    return doc
